"""UDP media transport end-to-end: real sockets → native parse → plane →
rewrite → real sockets.

Reference parity: the media half of test/singlenode_test.go TestSinglePublisher
— but over this build's plain-RTP UDP wire instead of Pion WebRTC.
"""

import asyncio
import socket

import numpy as np

from livekit_server_tpu.models import plane
from livekit_server_tpu.native import rtp as parser
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.udp import start_udp_transport
from tests.test_native import rtp_packet, vp8_payload

DIMS = plane.PlaneDims(rooms=2, tracks=4, pkts=8, subs=4)


async def test_udp_publish_forward_receive():
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    # free port
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        # control plane: room row 0, track col 0 published (audio), sub 1
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(room=0, track=0, is_video=False)

        # publisher + subscriber client sockets
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        got = []
        for i in range(5):
            pub.sendto(
                rtp_packet(sn=600 + i, ts=960 * i, ssrc=ssrc, audio_level=20,
                           payload=b"opus" + bytes([i])),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)  # let datagram_received run
            res = await runtime.step_once()
            transport.send_egress(res.egress)
            await asyncio.sleep(0.01)
            while True:
                try:
                    data, _ = sub.recvfrom(2048)
                    if not (192 <= data[1] <= 223):  # skip interleaved RTCP SRs
                        got.append(data)
                except BlockingIOError:
                    break

        assert transport.stats["rx"] == 5
        assert transport.stats["parse_errors"] == 0
        assert len(got) == 5
        # received packets are valid RTP with the original SNs and payloads
        from livekit_server_tpu.native import rtp as parser
        for i, data in enumerate(got):
            out = parser.parse_batch(
                data, np.asarray([0], np.int32), np.asarray([len(data)], np.int32)
            )[0]
            assert int(out["sn"]) == 600 + i
            off, ln = int(out["payload_off"]), int(out["payload_len"])
            assert data[off : off + ln] == b"opus" + bytes([i])
        pub.close()
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_vp8_rewrite_reaches_wire_across_layer_switch():
    """Simulcast layer switch: the device's rewritten picture ids must
    appear in the actual payload bytes on the wire, contiguous across the
    switch even though each source layer has its own pid space (the bug
    codecmunger/vp8.go:161 exists to prevent)."""
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=True)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc0 = transport.assign_ssrc(room=0, track=0, is_video=True, layer=0)
        ssrc1 = transport.assign_ssrc(room=0, track=0, is_video=True, layer=1)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        async def send_and_step(sn, ts, ssrc, pid, keyframe):
            pub.sendto(
                rtp_packet(
                    sn=sn, ts=ts, ssrc=ssrc, pt=96,
                    payload=vp8_payload(pid=pid, tl0=pid % 256, tid=0,
                                        keyidx=pid % 32, keyframe=keyframe),
                ),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress(res.egress)
            await asyncio.sleep(0.01)

        # Layer 0: keyframe + deltas, pid space starting at 1000.
        for i in range(6):
            await send_and_step(100 + i, 90 * i, ssrc0, 1000 + i, i == 0)
        # Layer 1 appears with keyframes, its own pid space at 5000; once
        # its bitrate registers the allocator upgrades and the selector
        # switches at a layer-1 keyframe.
        for i in range(30):
            await send_and_step(500 + i, 90 * (6 + i), ssrc1, 5000 + i, True)

        got = []
        while True:
            try:
                data = sub.recvfrom(4096)[0]
                if not (192 <= data[1] <= 223):  # skip interleaved RTCP SRs
                    got.append(data)
            except BlockingIOError:
                break
        assert len(got) >= 10, f"only {len(got)} packets received"
        pids = []
        for data in got:
            out = parser.parse_batch(
                data, np.asarray([0], np.int32), np.asarray([len(data)], np.int32),
                vp8_pts={96},
            )[0]
            assert int(out["payload_len"]) > 0
            pids.append(int(out["picture_id"]))
        # Wire picture ids must be CONTIGUOUS across the source switch —
        # no 1000→5000 jump may survive to the payload bytes.
        diffs = [b - a for a, b in zip(pids, pids[1:])]
        assert all(d == 1 for d in diffs), f"pids not contiguous: {pids}"
        pub.close()
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_sr_aligned_ts_across_layer_switch():
    """Publisher SRs for both simulcast layers put them on one timeline;
    the wire TS across a layer switch is then exactly continuous (no
    fallback one-frame jump) — forwarder.go:1456 processSourceSwitch."""
    from livekit_server_tpu.runtime.udp import build_sr, ntp_now

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=True)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc0 = transport.assign_ssrc(room=0, track=0, is_video=True, layer=0)
        ssrc1 = transport.assign_ssrc(room=0, track=0, is_video=True, layer=1)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        # Layer 1's RTP clock leads layer 0's by exactly 100_000 units:
        # same capture instant, offset TS spaces.
        L1_OFF = 100_000
        ntp = ntp_now()

        async def send_and_step(sn, ts, ssrc, pid, keyframe):
            pub.sendto(
                rtp_packet(
                    sn=sn, ts=ts, ssrc=ssrc, pt=96,
                    payload=vp8_payload(pid=pid, tl0=pid % 256, tid=0,
                                        keyidx=pid % 32, keyframe=keyframe),
                ),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress(res.egress)
            await asyncio.sleep(0.01)

        # Latch both SSRCs, then anchor both layers with SRs at one instant.
        await send_and_step(100, 0, ssrc0, 1000, True)
        await send_and_step(500, L1_OFF, ssrc1, 5000, True)
        pub.sendto(build_sr(ssrc0, ntp, 0, 1, 100), ("127.0.0.1", port))
        pub.sendto(build_sr(ssrc1, ntp, L1_OFF, 1, 100), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport._ts_delta[(0, 0, 1)] == L1_OFF
        assert transport._ts_delta[(0, 0, 0)] == 0

        # Frames advance at 3000 units/frame on the shared timeline.
        for i in range(1, 6):
            await send_and_step(100 + i, 3000 * i, ssrc0, 1000 + i, i == 1)
        for i in range(30):
            await send_and_step(
                501 + i, L1_OFF + 3000 * (6 + i), ssrc1, 5000 + i, True
            )

        tss = []
        while True:
            try:
                data = sub.recvfrom(4096)[0]
            except BlockingIOError:
                break
            if 192 <= data[1] <= 223:
                continue
            tss.append(int.from_bytes(data[4:8], "big"))
        assert len(tss) >= 10
        # Every wire TS sits on the 3000-unit shared grid — the switch
        # introduced no fallback jump and no L1_OFF leak.
        diffs = [b - a for a, b in zip(tss, tss[1:])]
        assert all(d % 3000 == 0 and 0 < d <= 9000 for d in diffs), (tss, diffs)
        pub.close()
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_punch_latches_only_real_source():
    """Egress addresses latch only from a punch datagram carrying a minted
    id, sent from the client's actual socket — a forged/unknown punch id is
    ignored (traffic-reflection hardening)."""
    from livekit_server_tpu.runtime.udp import PUNCH_ACK, PUNCH_REQ

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        pid = transport.assign_subscriber_punch(0, 1)
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)

        # wrong id: no latch, counted
        sub.sendto(PUNCH_REQ + (pid ^ 0xFFFF).to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert (0, 1) not in transport.sub_addrs
        assert transport.stats["bad_punch"] == 1

        # right id from the real socket: latches + acked
        sub.sendto(PUNCH_REQ + pid.to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport.sub_addrs[(0, 1)] == sub.getsockname()
        ack, _ = sub.recvfrom(2048)
        assert ack == PUNCH_ACK + pid.to_bytes(4, "big")

        # retry from the SAME socket (lost ack): re-acked, still latched
        sub.sendto(PUNCH_REQ + pid.to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        ack, _ = sub.recvfrom(2048)
        assert ack == PUNCH_ACK + pid.to_bytes(4, "big")

        # replay of the latched id from a DIFFERENT socket (an observer of
        # the cleartext handshake): rejected, latch unchanged
        evil = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        evil.bind(("127.0.0.1", 0))
        evil.sendto(PUNCH_REQ + pid.to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport.sub_addrs[(0, 1)] == sub.getsockname()
        assert transport.stats["bad_punch"] == 2
        evil.close()

        # the outstanding id is reused across subscription signals (even
        # after a latch — a routine second subscription must not kill an
        # id whose ack may still be in flight)
        assert transport.assign_subscriber_punch(0, 2) == transport.assign_subscriber_punch(0, 2)
        assert transport.assign_subscriber_punch(0, 1) == pid
        # …but an explicit re-punch request ROTATES it (NAT-rebind
        # recovery: old id dies, new unguessable one minted)
        pid2 = transport.assign_subscriber_punch(0, 1, rotate=True)
        assert pid2 != pid
        assert pid not in transport.punch_ids
        sub2 = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub2.bind(("127.0.0.1", 0))
        sub2.setblocking(False)
        sub2.sendto(PUNCH_REQ + pid2.to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport.sub_addrs[(0, 1)] == sub2.getsockname()
        sub2.close()

        # release clears the outstanding punch id too
        transport.release_subscriber(0, 1)
        assert pid2 not in transport.punch_ids
        assert (0, 1) not in transport._punch_by_sub
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_nack_rtx_end_to_end():
    """A subscriber loses a packet, NACKs it over RTCP, and receives the
    retransmit with the original munged SN and payload bytes (the
    buffer.go:673 → sequencer.go:263 replay loop — resolved host-side at
    RTCP time by the HostSequencer, no device round trip)."""
    from livekit_server_tpu.runtime.udp import build_nack

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(
        runtime.ingest, "127.0.0.1", port, nack_resolver=runtime.resolve_nacks
    )
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(room=0, track=0, is_video=False)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        for i in range(5):
            pub.sendto(
                rtp_packet(sn=600 + i, ts=960 * i, ssrc=ssrc, audio_level=20,
                           payload=b"opus" + bytes([i])),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress(res.egress)
            await asyncio.sleep(0.01)
        while True:  # drain the original deliveries ("the client lost 602")
            try:
                sub.recvfrom(2048)
            except BlockingIOError:
                break

        # The client NACKs munged SN 602 on its downtrack SSRC; the
        # retransmit comes back immediately (no tick in between).
        dt_ssrc = transport.subscriber_ssrc(0, 1, 0)
        sub.sendto(build_nack(0x1234, dt_ssrc, [602]), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport.stats["nacks_rx"] == 1
        assert runtime.stats.get("rtx_packets", 0) == 1
        data, _ = sub.recvfrom(2048)
        out = parser.parse_batch(
            data, np.asarray([0], np.int32), np.asarray([len(data)], np.int32)
        )[0]
        assert int(out["sn"]) == 602
        off, ln = int(out["payload_off"]), int(out["payload_len"])
        assert data[off : off + ln] == b"opus\x02"

        # Immediate duplicate NACK is RTT-throttled host-side.
        sub.sendto(build_nack(0x1234, dt_ssrc, [602]), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert runtime.stats.get("rtx_packets", 0) == 1  # no second replay
        try:
            sub.recvfrom(2048)
            raise AssertionError("throttled NACK produced a retransmit")
        except BlockingIOError:
            pass
        pub.close()
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_upstream_nack_generation():
    """A gap in the publisher's SN stream makes the server NACK the
    publisher over RTCP (buffer.go doNACKs), and a late arrival of the
    missing packet clears the request."""
    from livekit_server_tpu.runtime.udp import RTCP_RTPFB, parse_nack_fci

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=True)
        ssrc = transport.assign_ssrc(room=0, track=0, is_video=True)
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        pub.setblocking(False)

        pub.sendto(rtp_packet(sn=100, ssrc=ssrc, payload=b"a"), ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        # 101, 102 go missing:
        pub.sendto(rtp_packet(sn=103, ssrc=ssrc, payload=b"d"), ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        # Server sent a NACK for 101+102 back to the publisher's address.
        data, _ = pub.recvfrom(2048)
        assert data[1] == RTCP_RTPFB
        assert sorted(parse_nack_fci(data[12:])) == [101, 102]
        assert transport.stats["nacks_tx"] == 2

        # The publisher retransmits 101; it must land in ingest and leave
        # only 102 tracked as missing.
        pub.sendto(rtp_packet(sn=101, ssrc=ssrc, payload=b"b"), ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        assert 101 not in transport._rx_missing[ssrc]
        assert 102 in transport._rx_missing[ssrc]
        pub.close()
    finally:
        transport.transport.close()


async def test_udp_remb_feeds_bwe_estimate():
    """A REMB from the subscriber's own address lands as a BWE estimate
    sample; one from a spoofed source is rejected."""
    from livekit_server_tpu.runtime.udp import build_remb

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        transport.register_subscriber(0, 1, sub.getsockname())
        dt_ssrc = transport.subscriber_ssrc(0, 1, 0)

        sub.sendto(build_remb(0x1234, 2_500_000.0, [dt_ssrc]), ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        assert runtime.ingest._estimate_valid[0, 1]
        assert abs(runtime.ingest._estimate[0, 1] - 2_500_000.0) / 2_500_000.0 < 0.01

        evil = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        evil.bind(("127.0.0.1", 0))
        evil.sendto(build_remb(0x1234, 10.0, [dt_ssrc]), ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        assert runtime.ingest._estimate[0, 1] > 1_000_000  # unchanged
        assert transport.stats["addr_mismatch"] >= 1
        evil.close()
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_sender_report_and_rtt():
    """The server emits SRs per downtrack SSRC; a subscriber's RR echoing
    LSR/DLSR updates that sub's RTT (RFC 3550 A.8 → sequencer throttle)."""
    from livekit_server_tpu.runtime.udp import RTCP_RR, RTCP_SR, ntp_mid32, ntp_now

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(room=0, track=0, is_video=False)
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())
        transport._last_sr_ms = -1e9  # force the first SR immediately

        pub.sendto(rtp_packet(sn=600, ts=960, ssrc=ssrc, payload=b"x"),
                   ("127.0.0.1", port))
        await asyncio.sleep(0.02)
        res = await runtime.step_once()
        transport.send_egress(res.egress)
        await asyncio.sleep(0.02)

        sr = None
        while True:
            try:
                data, _ = sub.recvfrom(2048)
            except BlockingIOError:
                break
            if data[1] == RTCP_SR:
                sr = data
        assert sr is not None, "no SR emitted alongside egress"
        dt_ssrc = int.from_bytes(sr[4:8], "big")
        lsr = ntp_mid32(int.from_bytes(sr[8:16], "big"))

        # RR from the sub: fraction_lost 0, echoes LSR immediately (DLSR 0).
        block = (
            dt_ssrc.to_bytes(4, "big") + bytes([0]) + (0).to_bytes(3, "big")
            + (600).to_bytes(4, "big") + (0).to_bytes(4, "big")
            + lsr.to_bytes(4, "big") + (0).to_bytes(4, "big")
        )
        rr = bytes([0x80 | 1, RTCP_RR, 0, 7]) + (0x1234).to_bytes(4, "big") + block
        sub.sendto(rr, ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        # RTT = now - lsr - dlsr: tiny on loopback, so anything recorded
        # below the 100 ms default proves the path ran.
        assert runtime.ingest.rtt_ms[0, 1] < 100
        pub.close()
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_encrypted_media_end_to_end():
    """Secure wire: sealed RTP in, sealed egress out; a sniffer can read
    nothing and inject nothing (VERDICT: an unauthenticated cleartext
    media wire is not capability parity with DTLS-SRTP)."""
    from livekit_server_tpu.runtime.crypto import MediaCryptoClient, MediaCryptoRegistry
    from livekit_server_tpu.runtime.udp import UDPMediaTransport

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    loop = asyncio.get_running_loop()
    tr, transport = await loop.create_datagram_endpoint(
        lambda: UDPMediaTransport(runtime.ingest, crypto=reg, require_encryption=True),
        local_addr=("127.0.0.1", port),
    )
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)

        pub_sess = reg.mint()           # alice (publisher)
        sub_sess = reg.mint()           # bob (subscriber)
        transport.bind_sub_session(0, 1, sub_sess)
        ssrc = transport.assign_ssrc(0, 0, is_video=False, session=pub_sess)
        alice = MediaCryptoClient(pub_sess.key_id, pub_sess.key)
        bob = MediaCryptoClient(sub_sess.key_id, sub_sess.key)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        SECRET = b"top-secret-opus"
        wire_frames = []
        for i in range(5):
            pub.sendto(
                alice.seal(rtp_packet(sn=700 + i, ts=960 * i, ssrc=ssrc,
                                      payload=SECRET + bytes([i]))),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress(res.egress)
            await asyncio.sleep(0.01)
            while True:
                try:
                    wire_frames.append(sub.recvfrom(4096)[0])
                except BlockingIOError:
                    break
        assert len(wire_frames) >= 5
        # Sniffer view: every wire byte string is sealed — the payload
        # plaintext appears nowhere.
        for f in wire_frames:
            assert f[0] == 0x01 and SECRET not in f
        # The real subscriber decrypts fine and sees the original media.
        opened = [bob.open(f) for f in wire_frames]
        media = [o for o in opened if o is not None and not (192 <= o[1] <= 223)]
        assert len(media) == 5
        for i, m in enumerate(media):
            out = parser.parse_batch(
                m, np.asarray([0], np.int32), np.asarray([len(m)], np.int32)
            )[0]
            assert int(out["sn"]) == 700 + i
            off, ln = int(out["payload_off"]), int(out["payload_len"])
            assert m[off : off + ln] == SECRET + bytes([i])

        # Injection 1: plaintext RTP with the right SSRC → dropped.
        before = runtime.ingest._count.sum()
        pub.sendto(rtp_packet(sn=900, ssrc=ssrc, payload=b"evil"), ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        assert transport.stats["plaintext_drop"] == 1
        assert runtime.ingest._count.sum() == before
        # Injection 2: valid OTHER key, right SSRC → session mismatch.
        pub.sendto(bob.seal(rtp_packet(sn=901, ssrc=ssrc, payload=b"evil")),
                   ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        assert transport.stats["session_mismatch"] == 1
        assert runtime.ingest._count.sum() == before
        # Injection 3: replayed sealed publisher frame → rejected.
        replay = alice.seal(rtp_packet(sn=702, ssrc=ssrc, payload=b"x"))
        pub.sendto(replay, ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        pub.sendto(replay, ("127.0.0.1", port))
        await asyncio.sleep(0.03)
        assert transport.stats["bad_frame"] >= 1
        pub.close()
        sub.close()
    finally:
        tr.close()


async def test_tcp_media_fallback():
    """UDP-hostile network: a client speaks the same sealed frames over
    the TCP fallback (transportmanager.go:73 ladder) — publish and
    receive media with no UDP socket involved at all."""
    from livekit_server_tpu.runtime.crypto import MediaCryptoClient, MediaCryptoRegistry
    from livekit_server_tpu.runtime.tcp import start_tcp_transport
    from livekit_server_tpu.runtime.udp import UDPMediaTransport

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    udp = UDPMediaTransport(runtime.ingest, crypto=reg, require_encryption=True)
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    tcp = await start_tcp_transport(udp, reg, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        pub_sess = reg.mint()
        sub_sess = reg.mint()
        udp.bind_sub_session(0, 1, sub_sess)
        ssrc = udp.assign_ssrc(0, 0, is_video=False, session=pub_sess)
        alice = MediaCryptoClient(pub_sess.key_id, pub_sess.key)
        bob = MediaCryptoClient(sub_sess.key_id, sub_sess.key)

        def frame(b: bytes) -> bytes:
            return len(b).to_bytes(2, "big") + b

        a_r, a_w = await asyncio.open_connection("127.0.0.1", port)
        b_r, b_w = await asyncio.open_connection("127.0.0.1", port)
        # Bob announces himself with a sealed punch-style hello (any frame
        # binds the connection); use a tiny RTCP RR so dispatch is a no-op.
        hello = bytes([0x80, 201, 0, 1]) + (0x1234).to_bytes(4, "big")
        b_w.write(frame(bob.seal(hello)))
        await b_w.drain()
        await asyncio.sleep(0.1)
        assert udp.sub_addrs.get((0, 1)) == ("tcp", sub_sess.key_id)

        got = []

        async def reader():
            while True:
                hdr = await b_r.readexactly(2)
                data = await b_r.readexactly(int.from_bytes(hdr, "big"))
                inner = bob.open(data)
                if inner is not None and not (192 <= inner[1] <= 223):
                    got.append(inner)

        rt = asyncio.ensure_future(reader())
        for i in range(5):
            a_w.write(frame(alice.seal(
                rtp_packet(sn=800 + i, ts=960 * i, ssrc=ssrc,
                           payload=b"tcp" + bytes([i]))
            )))
            await a_w.drain()
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            udp.send_egress(res.egress)
            await asyncio.sleep(0.02)
        await asyncio.sleep(0.1)
        rt.cancel()
        assert len(got) == 5
        for i, m in enumerate(got):
            out = parser.parse_batch(
                m, np.asarray([0], np.int32), np.asarray([len(m)], np.int32)
            )[0]
            assert int(out["sn"]) == 800 + i
            off, ln = int(out["payload_off"]), int(out["payload_len"])
            assert m[off : off + ln] == b"tcp" + bytes([i])
        a_w.close()
        b_w.close()
    finally:
        tcp.close()


async def test_tcp_fallback_disables_twcc_feedback():
    """A subscriber that falls back from UDP to TCP must have
    fb_enabled cleared: TCP egress stamps no TWCC counters, so a stale
    True would starve its BWE budget to the floor (advisor r3 medium)."""
    from livekit_server_tpu.runtime.crypto import MediaCryptoClient, MediaCryptoRegistry
    from livekit_server_tpu.runtime.tcp import start_tcp_transport
    from livekit_server_tpu.runtime.udp import UDPMediaTransport
    from tests.conftest import free_port

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    udp = UDPMediaTransport(runtime.ingest, crypto=reg, require_encryption=True)
    port = free_port(socket.SOCK_STREAM)
    tcp = await start_tcp_transport(udp, reg, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        sub_sess = reg.mint()
        udp.bind_sub_session(0, 1, sub_sess)
        udp.register_subscriber(0, 1, ("127.0.0.1", 50000))
        assert bool(runtime.ingest.fb_enabled[0, 1])  # sealed UDP: TWCC on
        bob = MediaCryptoClient(sub_sess.key_id, sub_sess.key)
        r, w = await asyncio.open_connection("127.0.0.1", port)
        hello = bytes([0x80, 201, 0, 1]) + (0x1234).to_bytes(4, "big")
        sealed = bob.seal(hello)
        w.write(len(sealed).to_bytes(2, "big") + sealed)
        await w.drain()
        await asyncio.sleep(0.1)
        assert udp.sub_addrs.get((0, 1)) == ("tcp", sub_sess.key_id)
        assert not bool(runtime.ingest.fb_enabled[0, 1])  # TCP: TWCC off
        w.close()
        await asyncio.sleep(0.1)
        # Teardown removed the route entirely — still no feedback expected.
        assert (0, 1) not in udp.sub_addrs
        assert not bool(runtime.ingest.fb_enabled[0, 1])
    finally:
        tcp.close()


async def test_forward_latency_probe_measures_rx_to_wire():
    """The always-on latency probe: packets fed with an rx stamp must
    yield wire-out observations covering queueing + staging + device +
    send (VERDICT r3 missing #2 — a measured, not composed, latency)."""
    import time

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(room=0, track=0, is_video=False)
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        transport.register_subscriber(0, 1, sub.getsockname())

        dgrams = [
            rtp_packet(sn=100 + i, ts=960 * i, ssrc=ssrc, payload=b"x" * 40)
            for i in range(4)
        ]
        blob = np.frombuffer(b"".join(dgrams), np.uint8)
        lens = np.array([len(d) for d in dgrams], np.int32)
        offs = np.zeros(4, np.int32)
        np.cumsum(lens[:-1], out=offs[1:])
        t0 = time.perf_counter()
        transport.feed_batch(
            blob, offs, lens,
            np.full(4, 0x7F000001, np.uint32), np.full(4, 40000, np.uint16),
            4, t_rx=t0,
        )
        await asyncio.sleep(0.015)  # queueing the probe must account for
        res = await runtime.step_once()
        transport.send_egress_batch(res.egress_batch)
        probe = transport.fwd_latency
        assert probe.n == 4
        lo, hi = probe.quantile(0.0), probe.max_s
        # Latency must cover the deliberate 15 ms queueing wait and be
        # bounded by the whole test's elapsed time.
        assert hi >= 0.015
        assert hi <= time.perf_counter() - t0
        assert probe.summary()["p99_ms"] >= 15.0
        sub.close()
    finally:
        transport.transport.close()
        await runtime.stop()


async def test_udp_unknown_ssrc_dropped():
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.sendto(rtp_packet(ssrc=0xBEEF), ("127.0.0.1", port))
        pub.sendto(b"garbage", ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport.stats["unknown_ssrc"] == 1
        assert transport.stats["parse_errors"] == 1
        assert not runtime.ingest.valid.any()
        pub.close()
    finally:
        transport.transport.close()


async def test_udp_native_batch_egress():
    """The vectorized tick egress (send_egress_batch → one native
    assemble/seal/sendmmsg call) produces the same wire bytes as the
    per-packet path: sealed frames for keyed subscribers, cleartext for
    legacy ones, VP8 descriptors patched, and a correct WS-complement
    mask for subscribers with no media destination."""
    from livekit_server_tpu.runtime.crypto import MediaCryptoClient, MediaCryptoRegistry
    from livekit_server_tpu.runtime.udp import UDPMediaTransport

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    loop = asyncio.get_running_loop()
    tr, transport = await loop.create_datagram_endpoint(
        lambda: UDPMediaTransport(runtime.ingest, crypto=reg),
        local_addr=("127.0.0.1", port),
    )
    try:
        # One video track; three subscribers: sealed UDP, cleartext UDP,
        # and WS-only (no UDP address at all).
        runtime.set_track(0, 0, published=True, is_video=True)
        for sub_col in (0, 1, 2):
            runtime.set_subscription(0, 0, sub_col, subscribed=True)
        pub_ssrc = transport.assign_ssrc(0, 0, is_video=True)

        sealed_sess = reg.mint()
        sealed_sess.client_active = True
        transport.bind_sub_session(0, 0, sealed_sess)
        bob = MediaCryptoClient(sealed_sess.key_id, sealed_sess.key)

        socks = {}
        for sub_col in (0, 1):
            ss = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            ss.bind(("127.0.0.1", 0))
            ss.setblocking(False)
            socks[sub_col] = ss
            transport.register_subscriber(0, sub_col, ss.getsockname())

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))

        frames = {0: [], 1: []}
        handled_masks = []
        # Keyframes throughout: the allocator needs a few ticks of layer
        # liveness before the selector may lock, and it locks only at a
        # keyframe (simulcast.go:42).
        for i in range(10):
            pub.sendto(
                rtp_packet(sn=900 + i, ts=3000 * i, ssrc=pub_ssrc, pt=96,
                           payload=vp8_payload(pid=800 + i, tl0=7, tid=0,
                                               keyframe=True)),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            handled = transport.send_egress_batch(res.egress_batch)
            handled_masks.append((res.egress_batch, handled))
            await asyncio.sleep(0.01)
            for sub_col, ss in socks.items():
                while True:
                    try:
                        frames[sub_col].append(ss.recvfrom(4096)[0])
                    except BlockingIOError:
                        break

        assert len(frames[0]) >= 4 and len(frames[1]) >= 4
        # Sealed subscriber: every frame is AEAD-wrapped and opens cleanly
        # (interleaved sealed RTCP SRs are skipped).
        opened = []
        for f in frames[0]:
            assert f[0] == 0x01
            pt = bob.open(f)
            assert pt is not None
            if not 192 <= pt[1] <= 223:
                opened.append(pt)
        # Cleartext subscriber: plain RTP (version bits, VP8 PT); skip SRs.
        frames[1] = [f for f in frames[1] if not 192 <= f[1] <= 223]
        for f in frames[1]:
            assert f[0] >> 6 == 2 and (f[1] & 0x7F) == 96

        # Both views carry the same munged stream: contiguous SNs and
        # patched VP8 picture ids in the payload bytes.
        def fields(dgram):
            sn = int.from_bytes(dgram[2:4], "big")
            d = dgram[12:]
            pid = ((d[2] & 0x7F) << 8) | d[3]
            return sn, pid
        sealed_sns = [fields(p)[0] for p in opened]
        clear_sns = [fields(f)[0] for f in frames[1]]
        assert sealed_sns == sorted(sealed_sns)
        assert clear_sns == sealed_sns
        sealed_pids = [fields(p)[1] for p in opened]
        assert sealed_pids == sorted(sealed_pids)  # contiguous munged pids

        # WS complement: sub 2's entries are unhandled, subs 0/1 handled.
        batch, handled = handled_masks[-1]
        subs = np.asarray(batch.subs)
        assert handled[subs == 0].all() and handled[subs == 1].all()
        assert not handled[subs == 2].any()
        ws = batch.to_packets(~handled)
        assert ws and all(p.sub == 2 for p in ws)
    finally:
        tr.close()
        await runtime.stop()


async def test_pacer_spreads_tick_burst():
    """With the no-queue pacer enabled, a tick's egress spreads across
    the configured window instead of one burst (pkg/sfu/pacer no-queue):
    arrivals span a measurable interval and nothing is lost."""
    import time as _time

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        transport.pacer_spread_ms = 60.0
        transport.egress_threads = 1  # one worker: deterministic chunking
        # 4 audio tracks x 8 pkts x 4 subs = 128 entries > PACE_CHUNK(64),
        # so the native sender has 2 chunks and one inter-chunk gap.
        for t in range(4):
            runtime.set_track(0, t, published=True, is_video=False)
        ssrcs = [transport.assign_ssrc(0, t, is_video=False) for t in range(4)]
        subs = []
        for sub_col in range(4):
            ss = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            ss.bind(("127.0.0.1", 0))
            ss.setblocking(False)
            subs.append(ss)
            transport.register_subscriber(0, sub_col, ss.getsockname())
            for t in range(4):
                runtime.set_subscription(0, t, sub_col, subscribed=True)
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))

        for t, ssrc in enumerate(ssrcs):
            for i in range(8):
                pub.sendto(
                    rtp_packet(sn=100 + 8 * t + i, ts=960 * i, ssrc=ssrc,
                               audio_level=20, payload=b"pace%d%d" % (t, i)),
                    ("127.0.0.1", port),
                )
        await asyncio.sleep(0.03)
        res = await runtime.step_once()
        transport.send_egress_batch(res.egress_batch)

        # Poll arrivals with timestamps: the paced send runs on the pacer
        # worker thread while this loop observes the spread.
        arrivals = []
        deadline = _time.perf_counter() + 1.0
        while len(arrivals) < 128 and _time.perf_counter() < deadline:
            got_any = False
            for ss in subs:
                while True:
                    try:
                        d = ss.recvfrom(2048)[0]
                        if not 192 <= d[1] <= 223:
                            arrivals.append(_time.perf_counter())
                            got_any = True
                    except BlockingIOError:
                        break
            if not got_any:
                await asyncio.sleep(0.002)
        assert len(arrivals) == 128, f"paced egress lost packets: {len(arrivals)}/128"
        spread = arrivals[-1] - arrivals[0]
        assert spread >= 0.02, f"burst not spread: {spread * 1000:.1f} ms"
        assert transport._pace_pending is not None
        pub.close()
        for ss in subs:
            ss.close()
    finally:
        transport.transport.close()
        await runtime.stop()


async def test_leaky_bucket_pacer_defers_and_drains_fifo():
    """rtc.pacer=leaky-bucket: per-(room,sub) byte budgets gate the batch
    egress; over-budget packets defer and drain FIFO on later ticks
    (pkg/sfu/pacer leaky_bucket.go semantics at the host egress)."""
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    from tests.conftest import free_port

    port = free_port(socket.SOCK_DGRAM)
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    transport.pacer_mode = "leaky-bucket"
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(room=0, track=0, is_video=False)
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        # One tick carrying 4 packets of 8-byte payloads for one sub.
        for i in range(4):
            pub.sendto(rtp_packet(sn=100 + i, ts=960 * i, ssrc=ssrc,
                                  audio_level=20, payload=b"PAYLOAD" + bytes([i])),
                       ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        res = await runtime.step_once()
        assert len(res.egress_batch) == 4

        def recv_all():
            out = []
            while True:
                try:
                    d = sub.recvfrom(2048)[0]
                    if not 192 <= d[1] <= 223:
                        out.append(d)
                except BlockingIOError:
                    return out

        R, S = DIMS.rooms, DIMS.subs
        # Budget admits exactly 2 packets: budgets count wire bytes
        # (payload 8 B + WIRE_OVERHEAD_BYTES fixed per-packet overhead).
        from livekit_server_tpu.ops.pacer import WIRE_OVERHEAD_BYTES

        allowed = np.zeros((R, S), np.float32)
        allowed[0, 1] = 2.0 * (8 + WIRE_OVERHEAD_BYTES)
        transport.send_egress_batch(res.egress_batch, pacer_allowed=allowed)
        await asyncio.sleep(0.05)
        first = recv_all()
        assert len(first) == 2, f"admitted {len(first)} (want 2)"
        assert len(transport._pacer_queue) == 2
        assert transport.stats["pacer_deferred"] == 2

        # Next tick: fresh budget drains the deferred packets FIFO.
        empty = res.egress_batch.__class__(
            rooms=np.zeros(0, np.int32), tracks=np.zeros(0, np.int32),
            ks=np.zeros(0, np.int32), subs=np.zeros(0, np.int32),
            sn=np.zeros(0, np.int32), ts=np.zeros(0, np.int32),
            pid=np.zeros(0, np.int32), tl0=np.zeros(0, np.int32),
            keyidx=np.zeros(0, np.int32), payloads=res.egress_batch.payloads,
        )
        allowed[0, 1] = 1000.0
        transport.send_egress_batch(empty, pacer_allowed=allowed)
        await asyncio.sleep(0.05)
        second = recv_all()
        assert len(second) == 2 and not transport._pacer_queue
        sns = [int.from_bytes(d[2:4], "big") for d in first + second]
        assert sns == sorted(sns), f"FIFO violated: {sns}"
        pub.close()
        sub.close()
    finally:
        transport.transport.close()
        await runtime.stop()


async def test_twcc_feedback_caps_allocation_budget():
    """TWCC end-to-end (transport.go:253-374 seat): sealed egress counters
    → client feedback frames → host delay/rate reductions → device
    send-side estimator caps the allocator budget. The client volunteers
    NO estimate samples — a congested channel is detected purely from the
    sender's own measurements."""
    from livekit_server_tpu.runtime.crypto import (
        MediaCryptoClient,
        MediaCryptoRegistry,
        parse_counter,
    )
    from livekit_server_tpu.runtime.udp import (
        UDPMediaTransport,
        build_twcc_feedback,
    )
    from livekit_server_tpu.runtime.ingest import PacketIn
    from tests.conftest import free_port

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    port = free_port(socket.SOCK_DGRAM)
    loop = asyncio.get_running_loop()
    tr, transport = await loop.create_datagram_endpoint(
        lambda: UDPMediaTransport(runtime.ingest, crypto=reg, require_encryption=True),
        local_addr=("127.0.0.1", port),
    )
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        sub_sess = reg.mint()
        transport.bind_sub_session(0, 1, sub_sess)
        bob = MediaCryptoClient(sub_sess.key_id, sub_sess.key)
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())
        assert bool(runtime.ingest.fb_enabled[0, 1])  # sealed UDP path
        media_ssrc = transport.subscriber_ssrc(0, 1, 0)

        recv_us = 0
        for i in range(30):
            runtime.ingest.push(PacketIn(
                room=0, track=0, sn=100 + i, ts=960 * i, size=120,
                payload=b"y" * 120,
            ))
            res = await runtime.step_once()
            transport.send_egress_batch(res.egress_batch)
            await asyncio.sleep(0.01)
            ctrs = []
            while True:
                try:
                    f = sub.recvfrom(4096)[0]
                except BlockingIOError:
                    break
                c = parse_counter(f)
                if c is not None and bob.open(f) is not None:
                    ctrs.append(c)
            if ctrs:
                # Honest but congested receiver: every frame arrives 25 ms
                # later than the last while the sender paces at 10 ms —
                # delay variation +15 ms per packet, sustained.
                entries = []
                for c in sorted(ctrs):
                    recv_us += 25_000
                    entries.append((c, recv_us))
                fb = build_twcc_feedback(0xB0B, media_ssrc, entries)
                sub.sendto(bob.seal(fb), ("127.0.0.1", port))
                await asyncio.sleep(0.005)
        assert transport.stats.get("twcc_rx", 0) > 0
        committed = float(runtime._last_committed[0, 1])
        # Default (no estimate, no feedback) budget is the 7 Mbps initial;
        # measured congestion must have collapsed it.
        assert committed < 1_000_000.0, committed
        sub.close()
    finally:
        tr.close()
        await runtime.stop()


def _vp9_payload(sid=0, tid=0, keyframe=False, begin=True, end=True,
                 pid=77, tl0=3, fill=100):
    """VP9 payload descriptor (draft-ietf-payload-vp9) + filler bytes."""
    b0 = 0x80 | 0x20  # I (pid present) | L (layer indices)
    if not keyframe:
        b0 |= 0x40    # P: inter-predicted
    if begin:
        b0 |= 0x08    # B
    if end:
        b0 |= 0x04    # E
    d = bytearray([b0])
    d += bytes([0x80 | ((pid >> 8) & 0x7F), pid & 0xFF])  # 15-bit pid
    d.append((tid << 5) | ((sid & 7) << 1))               # T|U|SID|D
    d.append(tl0 & 0xFF)                                  # TL0PICIDX (F=0)
    d += bytes(fill)
    return bytes(d)


def _h264_payload(idr=False, fill=100):
    """Single-NALU H264 payload: IDR (5) or non-IDR slice (1)."""
    return bytes([0x65 if idr else 0x41]) + bytes(fill)


async def test_h264_simulcast_switch_on_wire():
    """H264 keyframe detection (NALU types) gates simulcast layer
    switching end-to-end: the selector locks a new spatial layer only at
    an IDR of that layer (the reference parses NALUs in buffer.go:599-671
    for exactly this)."""
    from livekit_server_tpu.runtime.udp import H264_PT
    from tests.conftest import free_port

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    port = free_port(socket.SOCK_DGRAM)
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=True)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        runtime.set_layer_caps(0, 0, 1, max_spatial=0)   # start at L0
        ssrc0 = transport.assign_ssrc(0, 0, True, layer=0, mime="video/h264")
        ssrc1 = transport.assign_ssrc(0, 0, True, layer=1, mime="video/h264")
        assert int(transport._track_pt[0, 0]) == H264_PT
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        L0, L1 = 100, 220  # distinguishable payload sizes on the wire

        def recv_sizes():
            out = []
            while True:
                try:
                    d = sub.recvfrom(4096)[0]
                    if not 192 <= d[1] <= 223:
                        out.append(len(d) - 12)
                except BlockingIOError:
                    return out

        async def tick(sn, idr0=False, idr1=False):
            pub.sendto(rtp_packet(sn=sn, ts=90 * sn, ssrc=ssrc0, pt=H264_PT,
                                  marker=1,
                                  payload=_h264_payload(idr0, L0 - 1)),
                       ("127.0.0.1", port))
            pub.sendto(rtp_packet(sn=sn, ts=90 * sn, ssrc=ssrc1, pt=H264_PT,
                                  marker=1,
                                  payload=_h264_payload(idr1, L1 - 1)),
                       ("127.0.0.1", port))
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress_batch(res.egress_batch)
            await asyncio.sleep(0.01)

        # Phase 1: periodic IDRs on layer 0 (a real encoder keys on PLI);
        # the selector locks L0 at the first IDR after the allocator has
        # measured bitrates. Only L0-sized packets flow.
        for sn in range(100, 112):
            await tick(sn, idr0=sn % 4 == 0, idr1=False)
        sizes = recv_sizes()
        assert sizes and all(s == L0 for s in sizes), sizes

        # Phase 2: raise the cap; WITHOUT an IDR on layer 1 the selector
        # must keep forwarding layer 0 (no unlocked switch mid-GOP).
        runtime.set_layer_caps(0, 0, 1, max_spatial=1)
        for sn in range(112, 118):
            await tick(sn, idr0=sn % 4 == 0)
        sizes = recv_sizes()
        assert sizes and all(s == L0 for s in sizes), sizes

        # Phase 3: IDR arrives on layer 1 → switch; L1 sizes appear and
        # L0 stops.
        await tick(118, idr1=True)
        for sn in range(119, 126):
            await tick(sn, idr1=sn % 4 == 0)
        sizes = recv_sizes()
        assert L1 in sizes, sizes
        assert sizes[-3:] == [L1] * 3, sizes
        pub.close()
        sub.close()
    finally:
        transport.transport.close()
        await runtime.stop()


async def test_vp9_ddless_svc_downswitch_on_wire():
    """Plain VP9 SVC (no dependency descriptor): spatial layers come from
    the VP9 picture header's SID (vp9.go:43 seat); capping a subscriber
    downswitches the onion to layers ≤ cap."""
    from livekit_server_tpu.runtime.udp import SVC_PT
    from tests.conftest import free_port

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    port = free_port(socket.SOCK_DGRAM)
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=True, is_svc=True)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(0, 0, True, svc=True, mime="video/vp9")
        assert int(transport._track_pt[0, 0]) == SVC_PT
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        SIZES = {0: 100, 1: 200, 2: 300}  # payload size per spatial layer

        def recv_sizes():
            out = []
            while True:
                try:
                    d = sub.recvfrom(4096)[0]
                    if not 192 <= d[1] <= 223:
                        out.append(len(d) - 12)
                except BlockingIOError:
                    return out

        sn = 100

        async def tick(keyframe=False):
            nonlocal sn
            ts = 90 * sn
            for sid in (0, 1, 2):
                pub.sendto(
                    rtp_packet(
                        sn=sn, ts=ts, ssrc=ssrc, pt=SVC_PT,
                        marker=sid == 2,
                        payload=_vp9_payload(
                            sid=sid, keyframe=keyframe and sid == 0,
                            pid=sn & 0x7FFF, fill=SIZES[sid] - 5,
                        ),
                    ),
                    ("127.0.0.1", port),
                )
                sn += 1
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress_batch(res.egress_batch)
            await asyncio.sleep(0.01)

        # Keyframe locks the onion at full height: all three layers flow.
        await tick(keyframe=True)
        for _ in range(5):
            await tick()
        sizes = recv_sizes()
        assert len(set(sizes)) == 3, sizes   # every spatial layer present

        # Cap to spatial 0: the onion sheds layers 1-2.
        runtime.set_layer_caps(0, 0, 1, max_spatial=0)
        for _ in range(8):
            await tick()
        recv_sizes()                  # drain the transition
        for _ in range(4):
            await tick()
        sizes = recv_sizes()
        assert sizes and len(set(sizes)) == 1, sizes  # only one layer size
        pub.close()
        sub.close()
    finally:
        transport.transport.close()
        await runtime.stop()


async def test_send_side_bwe_off_switch():
    """config rtc.congestion_control.send_side_bwe=false must keep
    fb_enabled off for an otherwise-eligible sealed-UDP subscriber (the
    operator opt-out; allocation falls back to client estimates)."""
    from livekit_server_tpu.runtime.crypto import MediaCryptoRegistry
    from livekit_server_tpu.runtime.udp import UDPMediaTransport
    from tests.conftest import free_port

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    port = free_port(socket.SOCK_DGRAM)
    loop = asyncio.get_running_loop()
    tr, transport = await loop.create_datagram_endpoint(
        lambda: UDPMediaTransport(runtime.ingest, crypto=reg, require_encryption=True),
        local_addr=("127.0.0.1", port),
    )
    try:
        transport.send_side_bwe = False
        transport.bind_sub_session(0, 1, reg.mint())
        transport.register_subscriber(0, 1, ("127.0.0.1", 50001))
        assert not bool(runtime.ingest.fb_enabled[0, 1])
        # Flipping it on and re-registering enables the path.
        transport.send_side_bwe = True
        transport.register_subscriber(0, 1, ("127.0.0.1", 50001))
        assert bool(runtime.ingest.fb_enabled[0, 1])
    finally:
        tr.close()
        await runtime.stop()


def test_probe_overflow_bin_reports_exact_max():
    """Samples beyond the histogram's 60 s top edge land in the overflow
    bin; quantiles that fall there must report the exact max, not the
    collapsed last-edge value."""
    from livekit_server_tpu.runtime.udp import ForwardLatencyProbe

    p = ForwardLatencyProbe()
    p.observe(np.full(100, 75.0))  # all beyond the top edge
    s = p.summary()
    assert s["p50_ms"] == s["p99_ms"] == s["max_ms"] == 75000.0
    # Mixed: in-range p50, overflow p99.
    p.reset()
    p.observe(np.concatenate([np.full(95, 0.010), np.full(5, 90.0)]))
    s = p.summary()
    assert 9.0 <= s["p50_ms"] <= 12.0
    assert s["p99_ms"] == 90000.0


def test_probe_summary_concurrent_with_observe():
    """summary()/quantile() snapshot under the probe lock: hammer observe
    from a thread while reading — derived stats must stay internally
    consistent (n == counts sum implied by mean/sum never torn)."""
    import threading

    from livekit_server_tpu.runtime.udp import ForwardLatencyProbe

    p = ForwardLatencyProbe()
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            p.observe(np.full(64, 0.005))

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(300):
            s = p.summary()
            if s["n"]:
                # mean of identical samples can only be exact if sum_s and
                # n were read from one consistent snapshot
                assert abs(s["mean_ms"] - 5.0) < 1e-6
    finally:
        stop.set()
        t.join()


async def test_probe_coverage_all_egress_paths():
    """VERDICT r4 #8: >=99% of wire egress must carry a nonzero rx stamp
    into the forward-latency probe across ALL THREE egress paths — UDP
    batch fast path, pacer-deferred cold path, and TCP fallback. The
    t_arr=0 sentinel makes silent coverage loss easy; this test fails if
    any path drops the stamp."""
    from livekit_server_tpu.ops.pacer import WIRE_OVERHEAD_BYTES
    from livekit_server_tpu.runtime.crypto import (
        MediaCryptoClient,
        MediaCryptoRegistry,
    )
    from tests.conftest import free_port

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    port = free_port(socket.SOCK_DGRAM)
    transport = await start_udp_transport(
        runtime.ingest, "127.0.0.1", port, crypto=reg
    )
    transport.pacer_mode = "leaky-bucket"
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)  # UDP sub
        runtime.set_subscription(0, 0, 2, subscribed=True)  # TCP sub
        ssrc = transport.assign_ssrc(room=0, track=0, is_video=False)
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())
        # TCP-fallback subscriber: a sealed sink keyed by session.
        sess = reg.mint()
        transport.bind_sub_session(0, 2, sess)
        tcp_frames = []
        transport.tcp_sinks[sess.key_id] = tcp_frames.append
        transport.register_subscriber(0, 2, ("tcp", sess.key_id))
        bob = MediaCryptoClient(sess.key_id, sess.key)

        R, S = DIMS.rooms, DIMS.subs
        udp_rx = 0
        n_ticks, per_tick = 6, 4
        for tick in range(n_ticks):
            for i in range(per_tick):
                pub.sendto(
                    rtp_packet(
                        sn=1000 + tick * per_tick + i, ts=960 * tick,
                        ssrc=ssrc, audio_level=20, payload=b"x" * 8,
                    ),
                    ("127.0.0.1", port),
                )
            await asyncio.sleep(0.03)
            res = await runtime.step_once()
            # Budget admits only half the UDP sub's packets per tick →
            # the rest defer and drain on later ticks (cold path).
            allowed = np.zeros((R, S), np.float32)
            allowed[0, 1] = (per_tick / 2 + tick) * (8 + WIRE_OVERHEAD_BYTES)
            transport.send_egress_batch(
                res.egress_batch, pacer_allowed=allowed
            )
            await asyncio.sleep(0.02)
            while True:
                try:
                    d = sub.recvfrom(2048)[0]
                    if not 192 <= d[1] <= 223:
                        udp_rx += 1
                except BlockingIOError:
                    break
        # Drain any still-deferred packets with generous budgets.
        empty = res.egress_batch.__class__(
            rooms=np.zeros(0, np.int32), tracks=np.zeros(0, np.int32),
            ks=np.zeros(0, np.int32), subs=np.zeros(0, np.int32),
            sn=np.zeros(0, np.int32), ts=np.zeros(0, np.int32),
            pid=np.zeros(0, np.int32), tl0=np.zeros(0, np.int32),
            keyidx=np.zeros(0, np.int32), payloads=res.egress_batch.payloads,
        )
        for _ in range(4):
            allowed = np.full((R, S), 1e6, np.float32)
            transport.send_egress_batch(empty, pacer_allowed=allowed)
            await asyncio.sleep(0.02)
        while True:
            try:
                d = sub.recvfrom(2048)[0]
                if not 192 <= d[1] <= 223:
                    udp_rx += 1
            except BlockingIOError:
                break
        tcp_media = sum(
            1 for f in tcp_frames
            if (inner := bob.open(f)) is not None
            and not 192 <= inner[1] <= 223
        )
        total_media = udp_rx + tcp_media
        n_sent = n_ticks * per_tick
        assert udp_rx == n_sent, f"UDP sub got {udp_rx}/{n_sent}"
        assert tcp_media == n_sent, f"TCP sub got {tcp_media}/{n_sent}"
        probe = transport.fwd_latency
        assert probe.n >= 0.99 * total_media, (
            f"probe covered {probe.n}/{total_media} egress packets — an "
            "egress path is dropping the rx stamp"
        )
        pub.close()
        sub.close()
    finally:
        transport.transport.close()
        await runtime.stop()
