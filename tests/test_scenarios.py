"""Scenario matrix: multi-publisher, auto-subscribe off, webhooks.

Reference parity: test/scenarios.go (multi-publisher matrices),
test/singlenode_test.go TestAutoSubscribe (auto_subscribe=0 joins get no
automatic subscriptions), test/webhook_test.go (in-test webhook receiver
validates signed events).
"""

import asyncio
import json

import aiohttp
from aiohttp import web

from tests.test_service import SignalClient, running_server


async def test_multi_publisher_matrix():
    """Three participants each publish audio; every one receives media
    from BOTH others (scenarios.go publish-to-all matrix)."""
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            clients = {}
            for name in ("p1", "p2", "p3"):
                c = SignalClient(s, server.port)
                await c.connect("matrix", name)
                clients[name] = c
            sids = {}
            for name, c in clients.items():
                await c.send_signal(
                    "add_track", {"cid": f"mic-{name}", "type": 0, "name": name}
                )
                tp = await c.wait_for("track_published")
                sids[name] = tp["track"]["sid"]
                await c.send_media(
                    cid=f"mic-{name}", sn=0, ts=0, payload=b"bind",
                    audio_level=20, frame_ms=20,
                )
            await asyncio.sleep(0.2)
            for i in range(1, 6):
                for name, c in clients.items():
                    await c.send_media(
                        cid=f"mic-{name}", sn=i, ts=960 * i,
                        payload=name.encode() + bytes([i]),
                        audio_level=20, frame_ms=20,
                    )
                await asyncio.sleep(0.03)
            deadline = asyncio.get_event_loop().time() + 5
            ok = False
            while not ok and asyncio.get_event_loop().time() < deadline:
                ok = all(
                    {sids[o] for o in sids if o != name}
                    <= {m["track_sid"] for m in c.media}
                    for name, c in clients.items()
                )
                await asyncio.sleep(0.05)
            for name, c in clients.items():
                got = {m["track_sid"] for m in c.media}
                expect = {sids[o] for o in sids if o != name}
                assert expect <= got, f"{name} missing {expect - got}"
                assert sids[name] not in got, f"{name} got its own media back"
            for c in clients.values():
                await c.close()


async def test_auto_subscribe_disabled():
    """auto_subscribe=0: no automatic subscription on publish; an explicit
    subscription signal starts media (singlenode_test.go auto-sub off)."""
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, server.port)
            bob = SignalClient(s, server.port)
            await alice.connect("nosub", "alice")
            await bob.connect("nosub", "bob", query="&auto_subscribe=0")

            await alice.send_signal("add_track", {"cid": "mic", "type": 0})
            tp = await alice.wait_for("track_published")
            sid = tp["track"]["sid"]
            await alice.send_media(cid="mic", sn=0, ts=0, payload=b"bind",
                                   audio_level=20, frame_ms=20)
            # Bob must NOT be auto-subscribed.
            await asyncio.sleep(0.4)
            assert not any("track_subscribed" in m for m in bob.signals)
            for i in range(1, 4):
                await alice.send_media(cid="mic", sn=i, ts=960 * i,
                                       payload=b"pre", audio_level=20, frame_ms=20)
            await asyncio.sleep(0.2)
            assert not bob.media, "media leaked to an unsubscribed participant"

            # Explicit subscription starts the stream.
            await bob.send_signal(
                "subscription", {"track_sids": [sid], "subscribe": True}
            )
            await bob.wait_for("track_subscribed")
            for i in range(4, 10):
                await alice.send_media(cid="mic", sn=i, ts=960 * i,
                                       payload=b"post", audio_level=20, frame_ms=20)
                await asyncio.sleep(0.03)
            media = await bob.wait_media(3)
            assert all(m["track_sid"] == sid for m in media)
            await alice.close()
            await bob.close()


async def test_webhooks_delivered_and_signed():
    """Lifecycle events reach a configured webhook URL with the sha256-
    signed JWT header (webhook_test.go; telemetry/webhook.py)."""
    import base64
    import hashlib
    import socket

    from livekit_server_tpu.auth import verify_token
    from tests.test_service import API_KEY, API_SECRET

    received: list[tuple[bytes, str]] = []

    async def hook(request: web.Request):
        received.append(
            (await request.read(), request.headers.get("Authorization", ""))
        )
        return web.Response(text="ok")

    hook_app = web.Application()
    hook_app.router.add_post("/hook", hook)
    runner = web.AppRunner(hook_app)
    await runner.setup()
    hs = socket.socket()
    hs.bind(("127.0.0.1", 0))
    hook_port = hs.getsockname()[1]
    hs.close()
    site = web.TCPSite(runner, "127.0.0.1", hook_port)
    await site.start()

    def add_hook(cfg):
        cfg.webhook.urls = [f"http://127.0.0.1:{hook_port}/hook"]

    try:
        async with running_server(configure=add_hook) as server:
            async with aiohttp.ClientSession() as s:
                alice = SignalClient(s, server.port)
                await alice.connect("hooked", "alice")
                deadline = asyncio.get_event_loop().time() + 5
                while (
                    not {json.loads(b)["event"] for b, _ in received}
                    >= {"room_started", "participant_joined"}
                    and asyncio.get_event_loop().time() < deadline
                ):
                    await asyncio.sleep(0.05)
                events = {json.loads(b)["event"] for b, _ in received}
                assert {"room_started", "participant_joined"} <= events, events
                # Signature: the JWT verifies under the API key and its
                # sha256 claim covers the RAW body bytes as sent (the
                # livekit webhook contract).
                body, auth = received[0]
                claims = verify_token(auth, {API_KEY: API_SECRET})
                digest = base64.b64encode(hashlib.sha256(body).digest()).decode()
                assert claims.sha256 == digest
                await alice.close()
    finally:
        await runner.cleanup()


async def test_churn_under_media_load():
    """Control-plane churn racing the media plane: participants join,
    publish, stream, unpublish, and leave across several rooms while
    other publishers keep streaming. Exercises slot reuse (track cols,
    sub cols), subscription fan-out during active ticks, and the per-sub
    device-state reset path — the §5.2 race surface, end-to-end."""
    from tests.test_service import SignalClient, running_server

    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            # Two long-lived publishers in two rooms stream throughout.
            stable = []
            for rname in ("churn-a", "churn-b"):
                p = SignalClient(s, server.port)
                await p.connect(rname, f"anchor-{rname}")
                await p.send_signal(
                    "add_track", {"cid": "mic", "type": 0, "name": "mic"}
                )
                await p.wait_for("track_published")
                stable.append(p)

            async def stream(p, base):
                for i in range(40):
                    await p.send_media(
                        cid="mic", sn=base + i, ts=960 * i,
                        payload=b"s" + bytes([i]), audio_level=20, frame_ms=20,
                    )
                    await asyncio.sleep(0.008)

            async def churn(room, tag):
                for j in range(3):
                    c = SignalClient(s, server.port)
                    await c.connect(room, f"{tag}-{j}")
                    await c.send_signal(
                        "add_track",
                        {"cid": f"m{j}", "type": 0, "name": "m"},
                    )
                    await c.wait_for("track_published")
                    for i in range(4):
                        await c.send_media(
                            cid=f"m{j}", sn=10 + i, ts=960 * i,
                            payload=b"c", audio_level=30, frame_ms=20,
                        )
                        await asyncio.sleep(0.005)
                    await c.close()

            await asyncio.gather(
                stream(stable[0], 1000),
                stream(stable[1], 2000),
                churn("churn-a", "ca"),
                churn("churn-b", "cb"),
                churn("churn-a", "ca2"),
            )
            # The plane survived: anchors still present, churners gone,
            # rooms intact, and slots were actually recycled. Removal is
            # asynchronous after the WS close (session worker observes the
            # closed channel on its own loop turns), so poll briefly.
            rm = server.room_manager
            assert set(rm.rooms) >= {"churn-a", "churn-b"}
            deadline = asyncio.get_event_loop().time() + 5.0
            def churners():
                return [
                    i
                    for rname in ("churn-a", "churn-b")
                    for i in rm.rooms[rname].participants
                    if i.startswith(("ca-", "cb-", "ca2-"))
                ]
            while churners():
                assert asyncio.get_event_loop().time() < deadline, churners()
                await asyncio.sleep(0.05)
            for rname in ("churn-a", "churn-b"):
                assert f"anchor-{rname}" in set(rm.rooms[rname].participants)
            assert rm.runtime.stats["fwd_packets"] > 0
            for p in stable:
                await p.close()
