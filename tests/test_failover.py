"""Node-death failover: lease expiry → takeover → restore from checkpoint.

Reference parity: redisrouter's RemoveDeadNodes plus the migration seeding
of participant.go:823, composed into an unattended path — no client join
is needed to re-home a dead node's rooms. The survivor's failover worker
(service/roommanager.py) notices the expired liveness lease, wins the
takeover lock, and restores the room row from the periodic checkpoint the
dead node published to the KV bus (runtime/supervisor.py cadence).

The node kill here is the fault-injection harness's non-graceful variant
(runtime/faultinject.py kill_node): heartbeats and the lease stop, the
bus socket drops, and NOTHING is cleaned up — exactly what a crashed host
looks like to the survivors.
"""

import asyncio

import aiohttp
import pytest

from livekit_server_tpu.routing.tcpbus import TCPBusClient
from livekit_server_tpu.runtime.faultinject import FaultInjector
from livekit_server_tpu.runtime.ingest import PacketIn
from livekit_server_tpu.service.server import create_server
from tests.conftest import free_port
from tests.test_multinode import start_bus
from tests.test_service import SignalClient, make_config


async def start_chaos_node(bus_port: int, *, lease_ttl: float = 1.0):
    """A node with failure-detection cadences tightened for test time:
    sub-second lease, fast failover scan, fast checkpoint cadence. The
    heartbeat interval must stay well inside the lease TTL or live nodes
    would flap dead between refreshes."""
    client = await TCPBusClient.connect("127.0.0.1", bus_port)
    cfg = make_config(free_port())
    cfg.kv.lease_ttl_s = lease_ttl
    cfg.kv.failover_interval_s = 0.15
    cfg.supervisor.checkpoint_interval_s = 0.25
    srv = create_server(cfg, bus=client)
    srv.router.stats_interval = 0.3  # heartbeat + lease refresh cadence
    await srv.start()
    return srv, client


async def _stop_quiet(srv) -> None:
    try:
        await srv.stop(force=True)
    except (ConnectionError, OSError):
        pass  # a killed node's bus is gone; cleanup calls fail fast


async def test_node_death_failover_restores_room_on_survivor():
    """Kill node A (non-graceful) with a room pinned to it and media
    state checkpointed: node B's failover worker adopts the room without
    any client action, the munger lane resumes from the checkpoint (the
    continued stream emits contiguous SNs, no reset), and the failover
    counter increments."""
    bus = await start_bus()
    srv_a = srv_b = None
    try:
        srv_a, _ = await start_chaos_node(bus.port)
        srv_b, _ = await start_chaos_node(bus.port)
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, srv_a.port)
            await alice.connect("chaos", "alice")
            row_a = srv_a.room_manager.rooms["chaos"].slots.row
            rt_a = srv_a.room_manager.runtime
            rt_a.set_track(row_a, 0, published=True, is_video=False)
            rt_a.set_subscription(row_a, 0, 1, subscribed=True)
            # A's serving loop carries the traffic (mixing step_once into
            # a served runtime reorders the pipelined fan-outs, which can
            # transiently run munger state BACKWARDS); munger state —
            # polled, not sampled — is the ground truth for what went out.
            for i in range(5):
                rt_a.ingest.push(PacketIn(room=row_a, track=0, sn=7000 + i,
                                          ts=960 * i, size=50, payload=b"a"))
                await asyncio.sleep(0.02)
            deadline = asyncio.get_event_loop().time() + 10
            while (int(rt_a.munger.last_sn[row_a, 0, 1]) < 7004
                   and asyncio.get_event_loop().time() < deadline):
                await asyncio.sleep(0.02)
            assert int(rt_a.munger.last_sn[row_a, 0, 1]) == 7004
            await alice.close()

            # Make sure the bus checkpoint reflects the final munger state
            # (the periodic cadence would get there too; this pins timing).
            await srv_a.room_manager.checkpoint_rooms()
            a_id = srv_a.router.local_node.node_id

            await FaultInjector().kill_node(srv_a)
            # The stale pin still names the dead node on the bus…
            assert await srv_b.router.get_node_for_room("chaos") == a_id

            # …until B's failover worker sees the lease expire and adopts.
            deadline = asyncio.get_event_loop().time() + 15
            while ("chaos" not in srv_b.room_manager.rooms
                   and asyncio.get_event_loop().time() < deadline):
                await asyncio.sleep(0.05)
            assert "chaos" in srv_b.room_manager.rooms, "failover never happened"
            assert (await srv_b.router.get_node_for_room("chaos")
                    == srv_b.router.local_node.node_id)

            rt_b = srv_b.room_manager.runtime
            row_b = srv_b.room_manager.rooms["chaos"].slots.row
            # Munger lane restored from the checkpoint, not reset.
            assert int(rt_b.munger.last_sn[row_b, 0, 1]) == 7004
            # The continued stream emits contiguous, monotonic SNs across
            # the node death (subscribers re-subscribe after failover, as
            # after migration — masks deliberately don't travel). B's
            # serving loop carries the traffic — stepping manually here
            # would race its pipelined fan-out and scramble arrival order.
            rt_b.set_subscription(row_b, 0, 1, subscribed=True)
            got_b = []
            rt_b.on_tick(lambda res: got_b.extend(
                p.sn for p in res.egress if p.sub == 1 and p.room == row_b))
            for i in range(5, 10):
                rt_b.ingest.push(PacketIn(room=row_b, track=0, sn=7000 + i,
                                          ts=960 * i, size=50, payload=b"b"))
                await asyncio.sleep(0.02)
            deadline = asyncio.get_event_loop().time() + 5
            while (len(got_b) < 5
                   and asyncio.get_event_loop().time() < deadline):
                await asyncio.sleep(0.05)
            assert got_b == list(range(7005, 7010))
            assert int(rt_b.munger.last_sn[row_b, 0, 1]) == 7009
            assert srv_b.telemetry.counters["livekit_room_failovers_total"] >= 1
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                await _stop_quiet(srv)
        bus.close()


@pytest.mark.slow
async def test_soak_lossy_ingest_stays_healthy():
    """Soak: hundreds of ticks of seeded drop+duplicate chaos at the
    ingest boundary — the plane keeps forwarding, per-sub egress SNs stay
    strictly increasing (drops gap, dups dedup), and accounting matches
    the injector's tally."""
    from livekit_server_tpu.models import plane
    from livekit_server_tpu.runtime import PlaneRuntime
    from livekit_server_tpu.runtime.faultinject import FaultSpec

    dims = plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4)
    rt = PlaneRuntime(dims, tick_ms=10)
    inj = FaultInjector(FaultSpec(seed=42, drop_pct=0.1, dup_pct=0.1))
    rt.fault = inj
    rt.ingest.fault = inj
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)

    egressed = []
    for i in range(400):
        rt.ingest.push(PacketIn(room=0, track=0, sn=(20000 + i) & 0xFFFF,
                                ts=960 * i, size=50, payload=b"s"))
        res = await rt.step_once()
        egressed += [p.sn for p in res.egress if p.sub == 1]

    assert inj.stats.dropped > 10 and inj.stats.duplicated > 10
    # Every non-dropped packet went out exactly once, in order.
    assert len(egressed) == 400 - inj.stats.dropped
    assert all(b > a for a, b in zip(egressed, egressed[1:]))
