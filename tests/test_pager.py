"""Paged room-state plane: host buddy allocator (rounding, churn,
fragmentation, compaction, exhaustion), the free-page re-init
invariant, page-handle epoch discipline, `plane.pager_*` config
validation, page-backed admission headroom, and the runtime acceptance
criteria — dense↔paged bit-parity on a mixed-size room population,
layout-independent checkpoints, cross-layout room migration,
grow-on-join across a page boundary, and a seeded page-table SDC
drill (detect → table repair → room quarantine → row repair)."""

from __future__ import annotations

import numpy as np
import pytest

from livekit_server_tpu.config import ConfigError, load_config
from livekit_server_tpu.models import paged, plane
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.governor import OverloadGovernor
from livekit_server_tpu.runtime.ingest import PacketIn
from livekit_server_tpu.runtime.integrity import BIT_TABLE, IntegrityMonitor
from livekit_server_tpu.runtime.paged_runtime import PagedPlaneRuntime
from livekit_server_tpu.runtime.pager import RoomPager, StalePageError
from livekit_server_tpu.runtime.slots import CapacityError, PagedSlotAllocator

DD = plane.PlaneDims(rooms=4, tracks=4, pkts=4, subs=8)
PD = paged.PagedDims(rooms=4, tracks=4, pkts=4, subs=8,
                     tpage=2, spage=4, pool_pages=16)
PD_WIDE = paged.PagedDims(rooms=4, tracks=4, pkts=4, subs=8,
                          tpage=2, spage=4, pool_pages=32)

# The mixed-size fixture: a 2-person room, the full-width room, and an
# odd-extent room whose sub count does not land on a page boundary.
ROOMS = [("a", 1, 2), ("b", 4, 8), ("c", 2, 5)]


def _pager(**kw) -> RoomPager:
    args = dict(rooms=4, tracks=4, subs=8, tpage=2, spage=4, pool_pages=16)
    args.update(kw)
    return RoomPager(**args)


# -- host allocator ----------------------------------------------------------

def test_alloc_page_rounding_and_slack():
    pg = _pager()
    assert pg.alloc_room(0, tracks=1, subs=2) == (2, 4)   # one page
    assert pg.alloc_room(1, tracks=4, subs=8) == (4, 8)   # full 2x2 grid
    assert pg.alloc_room(2, tracks=2, subs=5) == (2, 8)   # 1x2, subs round up
    st = pg.stats()
    assert st["pages_mapped"] == 1 + 4 + 2
    # room 2's 2-page grid reserved a pow2 run of 2 — no slack there; the
    # 1-page and 4-page rooms are exact too.
    assert st["internal_slack"] == st["pages_used"] - st["pages_mapped"]
    assert len(pg.pages_of_room(1)) == 4
    assert pg.extent(2) == (2, 8)
    # every mapped page's inverse maps agree with the room grids
    for row in (0, 1, 2):
        for p in pg.pages_of_room(row):
            assert pg.room_of_page(int(p)) == row


def test_buddy_coalesces_back_to_one_run_after_churn():
    pg = _pager()
    for round_ in range(3):
        for row, (_, tr, sb) in enumerate(ROOMS):
            pg.alloc_room(row, tracks=tr, subs=sb)
        for row in range(len(ROOMS)):
            pg.release_room(row)
    st = pg.stats()
    assert st["pages_used"] == 0
    assert st["pages_free"] == 16
    # full coalesce: one max-order free run, zero external fragmentation
    assert st["free_runs_by_order"] == {4: 1}
    assert st["fragmentation_ratio"] == 0.0
    assert st["allocs"] == 9 and st["frees"] == 9


def test_exhaustion_is_atomic_and_counted():
    # 1-page rooms over a 4-page pool: the 5th room must be refused
    # without disturbing the 4 resident ones.
    pg = RoomPager(rooms=8, tracks=2, subs=4, tpage=2, spage=4, pool_pages=4)
    for row in range(4):
        pg.alloc_room(row)
    before = pg.stats()
    with pytest.raises(CapacityError):
        pg.alloc_room(4)
    after = pg.stats()
    assert after["alloc_failures"] == 1
    assert after["pages_used"] == before["pages_used"] == 4
    assert len(pg.pages_of_room(4)) == 0
    # the failed alloc must leave no queued device events for room 4
    delta = pg.drain_delta()
    assert 4 not in delta.rooms.tolist()


def test_grow_keeps_existing_pages_and_fails_at_old_extent():
    pg = _pager()
    pg.alloc_room(0, tracks=1, subs=2)
    old_pages = set(pg.pages_of_room(0).tolist())
    ext = pg.grow_room(0, subs=8)
    assert ext == (2, 8)
    # grow never relocates: the original page survives in place
    assert old_pages <= set(pg.pages_of_room(0).tolist())
    assert pg.stats()["grows"] == 1

    # exhaustion mid-grow leaves the room at its old extent (tiny pool:
    # the 3 new grid cells need a 4-page run that does not exist)
    pg2 = RoomPager(rooms=2, tracks=4, subs=8, tpage=2, spage=4, pool_pages=4)
    pg2.alloc_room(0, tracks=1, subs=2)
    with pytest.raises(CapacityError):
        pg2.grow_room(0, tracks=4, subs=8)
    assert pg2.extent(0) == (2, 4)
    assert pg2.pages_reserved == 1


def test_compaction_packs_pool_and_reports_moves():
    pg = _pager()
    for row, (_, tr, sb) in enumerate(ROOMS):
        pg.alloc_room(row, tracks=tr, subs=sb)
    pg.drain_delta()
    # free the small rooms around the big one -> external fragmentation
    pg.release_room(0)
    pg.release_room(2)
    epoch_before = pg.epoch
    moves = pg.compact()
    assert pg.epoch > epoch_before
    assert len(moves) == 4                      # room 1's full grid moved
    dsts = sorted(d for _, d in moves)
    assert dsts == [0, 1, 2, 3]                 # packed to the pool bottom
    st = pg.stats()
    assert st["pages_used"] == 4
    # free space is fully buddy-coalesced above the live run: one run
    # per order, nothing stranded between rooms
    assert st["free_runs_by_order"] == {2: 1, 3: 1}
    assert st["compactions"] == 1
    # grids and inverse maps stayed consistent through the relocation
    for p in pg.pages_of_room(1):
        assert pg.room_of_page(int(p)) == 1


def test_freed_page_remapped_by_compaction_is_not_reinit():
    """Regression: a page released to the freed queue and then picked as
    a compaction move DESTINATION before the drain must not appear in
    freed_pages — the device re-init runs after the move replay and
    would wipe the relocated room state."""
    pg = _pager()
    for row, (_, tr, sb) in enumerate(ROOMS):
        pg.alloc_room(row, tracks=tr, subs=sb)
    pg.drain_delta()
    pg.release_room(0)
    pg.release_room(2)
    moves = pg.compact()
    dsts = {d for _, d in moves}
    # precondition: the hazard actually occurs in this scenario
    assert pg._freed & dsts, "scenario no longer exercises freed∩move-dst"
    delta = pg.drain_delta()
    freed = set(delta.freed_pages.tolist())
    assert not (freed & dsts)
    for p in freed:
        assert pg.pg_room[p] < 0                # only unmapped pages re-init
    # the vacated move sources do re-init (their stale state must not
    # forward if the pool hands them out again)
    assert freed == {s for s, _ in moves} - dsts


def test_page_handle_epoch_discipline():
    pg = _pager()
    pg.alloc_room(0)
    minted = pg.epoch
    pages = pg.pages_of_room(0)
    pg.check_epoch(minted)                      # no churn: still valid
    pg.alloc_room(1)                            # structural change
    with pytest.raises(StalePageError):
        pg.check_epoch(minted)
    # re-mint is the other sanctioned recovery
    assert np.array_equal(pg.pages_of_room(0), pages)
    pg.check_epoch(pg.epoch)


def test_pager_ctor_validation():
    with pytest.raises(ValueError):
        _pager(tpage=3)                         # not pow2
    with pytest.raises(ValueError):
        _pager(tpage=8)                         # does not divide tracks=4
    with pytest.raises(ValueError):
        _pager(spage=64, subs=64)               # sub page > mask word
    with pytest.raises(ValueError):
        _pager(pool_pages=12)                   # not pow2


# -- config knobs ------------------------------------------------------------

def test_pager_config_validation():
    cfg = load_config(yaml_text="""
development: true
plane:
  pager_enabled: true
  pager_tpage: 4
  pager_spage: 8
  pager_pool_pages: 256
""")
    assert cfg.plane.pager_enabled and cfg.plane.pager_pool_pages == 256

    with pytest.raises(ConfigError, match="pager_tpage must be a power"):
        load_config(yaml_text="development: true\nplane:\n"
                              "  pager_enabled: true\n  pager_tpage: 3")
    # pow2 and dividing the sub axis, but wider than the 32-bit mask word
    with pytest.raises(ConfigError, match="pager_spage must divide 32"):
        load_config(yaml_text="development: true\nplane:\n"
                              "  subs_per_room: 64\n"
                              "  pager_enabled: true\n  pager_spage: 64")
    with pytest.raises(ConfigError, match="pager_pool_pages"):
        load_config(yaml_text="development: true\nplane:\n"
                              "  pager_enabled: true\n  pager_pool_pages: 100")
    # divisor check against the actual plane axes
    with pytest.raises(ConfigError, match="must divide plane.subs_per_room"):
        load_config(yaml_text="development: true\nplane:\n"
                              "  subs_per_room: 20\n  pager_enabled: true")
    # knobs are inert while the pager is off
    cfg = load_config(yaml_text="development: true\nplane:\n  pager_tpage: 3")
    assert not cfg.plane.pager_enabled


# -- admission on real page headroom ----------------------------------------

def test_pool_exhaustion_denies_room_admission():
    # Every room is exactly one page; a 2-page pool admits two rooms.
    dims = paged.PagedDims(rooms=8, tracks=2, pkts=4, subs=4,
                           tpage=2, spage=4, pool_pages=2)
    rt = PagedPlaneRuntime(dims, tick_ms=10)
    gov = OverloadGovernor(rt)
    assert gov.should_admit("room")
    rt.slots.alloc_room("a")
    rt.slots.alloc_room("b")
    occ = rt.occupancy()
    # rows remain, but the page pool is the binding constraint
    assert occ["rooms_used"] == 2 < occ["rooms_capacity"]
    assert occ["pages_free"] == 0 and occ["admittable_rooms"] == 0
    assert not gov.should_admit("room")
    assert gov.should_admit("join")             # only NEW rooms are refused
    with pytest.raises(CapacityError):
        rt.slots.alloc_room("c")
    # the failed alloc must not leak the room row
    assert rt.occupancy()["rooms_used"] == 2
    rt.slots.release_room("a")
    assert rt.occupancy()["admittable_rooms"] == 1
    assert gov.should_admit("room")


def test_paged_allocator_grows_columns_through_pager():
    pg = _pager()
    slots = PagedSlotAllocator(pg)
    s = slots.alloc_room("r")
    assert (s.tracks.capacity, s.subs.capacity) == (2, 4)  # one-page extent
    for i in range(5):
        s.alloc_sub(f"p{i}")                    # 5th sub crosses spage=4
    assert s.subs.capacity == 8
    assert pg.extent(s.row).subs == 8
    occ = slots.occupancy()
    assert occ["subs_used"] == 5 and occ["subs_capacity"] == 8


# -- runtime: parity / checkpoints / migration / chaos -----------------------

def _setup_rooms(rt) -> None:
    for name, tr, sb in ROOMS:
        s = rt.slots.alloc_room(name)
        for i in range(tr):
            s.alloc_track(f"t{i}")
        for i in range(sb):
            s.alloc_sub(f"p{i}")
    rt.set_track(0, 0, published=True, is_video=True)
    rt.set_subscription(0, 0, 1, subscribed=True)
    rt.set_track(1, 0, published=True, is_video=True)
    rt.set_track(1, 3, published=True, is_video=False)
    for sub in range(8):
        rt.set_subscription(1, 0, sub, subscribed=True)
    rt.set_subscription(1, 3, 2, subscribed=True)
    rt.set_track(2, 1, published=True, is_video=False)
    rt.set_subscription(2, 1, 4, subscribed=True)


def _push(rt, tick: int) -> None:
    for room, track, base in [(0, 0, 100), (1, 0, 500), (1, 3, 900),
                              (2, 1, 1300)]:
        for j in range(2):
            sn = base + tick * 2 + j
            rt.ingest.push(PacketIn(
                room=room, track=track, sn=sn & 0xFFFF,
                ts=(960 * (tick * 2 + j)) & 0xFFFFFFFF,
                size=120, payload=b"x" * 120,
                keyframe=(tick == 0 and j == 0),
                audio_level=-(30 + (sn % 20)),
            ))


async def _run_ticks(rt, n: int, start: int = 0) -> None:
    for t in range(start, start + n):
        _push(rt, t)
        await rt.step_once()


def _capture(rt, log: list):
    orig = rt._unpack_outputs

    def wrapped(buf):
        out = orig(buf)
        log.append(out)
        return out

    rt._unpack_outputs = wrapped


def _round_up(n: int, p: int) -> int:
    return -(-n // p) * p


def _assert_outputs_match(tick: int, a, b) -> None:
    """a: dense logical outputs, b: paged logical outputs. Globally
    computed fields must match exactly; per-room fields must match
    within each room's PAGE-ROUNDED extent (outside it the paged layout
    has no backing state and reports the init fill)."""
    for f in ("send_bits", "drop_bits", "switch_bits", "need_keyframe",
              "speaker_levels", "speaker_tracks", "fwd_packets", "fwd_bytes"):
        va, vb = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert np.array_equal(va, vb), (tick, f)
    exts = {row: (tr, sb) for row, (_, tr, sb) in enumerate(ROOMS)}
    for f in ("congested", "committed_bps", "pacer_allowed", "deficient",
              "sub_quality"):
        va, vb = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        for r, (_, sb) in exts.items():
            sb_p = _round_up(sb, PD.spage)
            assert np.array_equal(va[r, :sb_p], vb[r, :sb_p]), (tick, f, r)
    for f in ("track_mos", "track_quality", "layer_live", "layer_fps",
              "track_loss_pct", "track_jitter_ms", "track_bps",
              "red_sn", "red_off", "red_ok"):
        va, vb = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        for r, (tr, _) in exts.items():
            tr_p = _round_up(tr, PD.tpage)
            assert np.array_equal(va[r, :tr_p], vb[r, :tr_p]), (tick, f, r)
    va, vb = np.asarray(a.target_layers), np.asarray(b.target_layers)
    for r, (tr, sb) in exts.items():
        tr_p, sb_p = _round_up(tr, PD.tpage), _round_up(sb, PD.spage)
        assert np.array_equal(va[r, :sb_p, :tr_p], vb[r, :sb_p, :tr_p]), \
            (tick, "target_layers", r)


async def test_dense_vs_paged_bit_parity_mixed_sizes():
    """The acceptance gate: the pooled layout is a pure re-arrangement —
    every tick decision on the mixed-size fixture is bit-identical to
    the dense plane, including egress sequence numbers."""
    dense = PlaneRuntime(DD, tick_ms=10)
    prt = PagedPlaneRuntime(PD, tick_ms=10)
    dlog, plog = [], []
    _capture(dense, dlog)
    _capture(prt, plog)
    _setup_rooms(dense)
    _setup_rooms(prt)
    for tick in range(12):
        _push(dense, tick)
        _push(prt, tick)
        rd = await dense.step_once()
        rp = await prt.step_once()
        _assert_outputs_match(tick, dlog[-1], plog[-1])
        assert rd.fwd_packets == rp.fwd_packets
        assert np.array_equal(np.asarray(rd.egress_batch.sn),
                              np.asarray(rp.egress_batch.sn)), tick
    assert dense.stats["fwd_packets"] == prt.stats["fwd_packets"] > 0


async def test_checkpoint_byte_parity_across_pool_layouts():
    """Checkpoints serialize LOGICAL rows, so the blob is independent of
    the pool geometry — and restoring into a different layout then
    ticking stays bit-identical to the source runtime."""
    p1 = PagedPlaneRuntime(PD, tick_ms=10)
    _setup_rooms(p1)
    await _run_ticks(p1, 8)
    blob1 = p1.encode_snapshot(p1.snapshot())

    p2 = PagedPlaneRuntime(PD_WIDE, tick_ms=10)
    _setup_rooms(p2)
    await _run_ticks(p2, 8)
    assert p2.encode_snapshot(p2.snapshot()) == blob1

    # restore the 16-page blob into a fresh 32-page runtime and diverge-check
    p3 = PagedPlaneRuntime(PD_WIDE, tick_ms=10)
    _setup_rooms(p3)
    p3.restore(p3.decode_snapshot(blob1))
    await _run_ticks(p1, 4, start=8)
    await _run_ticks(p3, 4, start=8)
    assert p1.encode_snapshot(p1.snapshot()) == p3.encode_snapshot(p3.snapshot())


def _alloc_full_room(rt, name: str):
    s = rt.slots.alloc_room(name)
    for i in range(4):
        s.alloc_track(f"t{i}")
    for i in range(8):
        s.alloc_sub(f"p{i}")
    return s


async def test_room_migration_across_layouts():
    """snapshot_room/restore_room move a room dense→paged and back with
    no bit drift (reference: a dense→dense restore of the same snapshot,
    since restore_room clears subscription masks on every layout)."""
    dense = PlaneRuntime(DD, tick_ms=10)
    _setup_rooms(dense)
    await _run_ticks(dense, 8)
    room_snap = dense.snapshot_room(1)

    prt = PagedPlaneRuntime(PD, tick_ms=10)
    s = _alloc_full_room(prt, "b")
    prt.restore_room(s.row, room_snap)
    paged_back = prt.snapshot_room(s.row)

    dref = PlaneRuntime(DD, tick_ms=10)
    sr = _alloc_full_room(dref, "b")
    dref.restore_room(sr.row, room_snap)
    ref = dref.snapshot_room(sr.row)
    for i, (x, y) in enumerate(zip(ref["arrays"], paged_back["arrays"])):
        assert np.array_equal(np.asarray(x), np.asarray(y)), i

    # paged -> dense direction round-trips too
    d2 = PlaneRuntime(DD, tick_ms=10)
    s2 = _alloc_full_room(d2, "b")
    d2.restore_room(s2.row, paged_back)
    for i, (x, y) in enumerate(zip(paged_back["arrays"],
                                   d2.snapshot_room(s2.row)["arrays"])):
        assert np.array_equal(np.asarray(x), np.asarray(y)), i


async def test_compaction_preserves_live_room_state():
    """Release the rooms around a live one, compact (its pages relocate
    into the freed bottom of the pool), and the room's logical state is
    bit-identical — the device-move + no-reinit-of-mapped-pages path."""
    prt = PagedPlaneRuntime(PD, tick_ms=10)
    _setup_rooms(prt)
    await _run_ticks(prt, 5)
    before = prt.snapshot_room(1)
    prt.slots.release_room("a")
    prt.slots.release_room("c")
    moves = prt.compact()                       # returns queued move count
    assert moves > 0
    after = prt.snapshot_room(1)
    for i, (x, y) in enumerate(zip(before["arrays"], after["arrays"])):
        assert np.array_equal(np.asarray(x), np.asarray(y)), i
    # and the plane still ticks cleanly on the compacted layout
    _push(prt, 5)
    res = await prt.step_once()
    assert res.fwd_packets > 0
    # Recompile watchdog: the first post-compaction tick above paid any
    # new pow2-bucket compiles; steady state on the compacted layout
    # must then hold the cache (zero XLA compiles per tick).
    prt.mark_warm()
    await _run_ticks(prt, 3, start=6)
    assert prt.compile_ledger.post_warmup == 0


async def test_grow_on_join_across_page_boundary():
    """A join past the room's current sub extent grows the page grid
    mid-stream; forwarding to the new subscriber works on the next tick."""
    prt = PagedPlaneRuntime(PD, tick_ms=10)
    s = prt.slots.alloc_room("g")
    s.alloc_track("t0")
    for i in range(3):
        s.alloc_sub(f"p{i}")
    prt.set_track(0, 0, published=True, is_video=False)
    prt.set_subscription(0, 0, 0, subscribed=True)

    async def tick(t):
        for j in range(2):
            prt.ingest.push(PacketIn(
                room=0, track=0, sn=100 + t * 2 + j, ts=960 * (t * 2 + j),
                size=90, payload=b"y" * 90, audio_level=-25))
        return await prt.step_once()

    for t in range(4):
        await tick(t)
    assert prt.pager.extent(0) == (2, 4)        # one page so far
    for i in range(3, 7):
        s.alloc_sub(f"p{i}")                    # crosses spage=4
    assert prt.pager.extent(0) == (2, 8)
    prt.set_subscription(0, 0, 6, subscribed=True)
    fwd = 0
    # First tick on the grown extent pays the new pow2 bucket's compile;
    # after that the watchdog must see a held cache (GC11 runtime half).
    res = await tick(4)
    fwd += res.fwd_packets
    prt.mark_warm()
    for t in range(5, 8):
        res = await tick(t)
        fwd += res.fwd_packets
    assert prt.compile_ledger.post_warmup == 0
    assert fwd > 0
    assert prt.pager.stats()["grows"] == 1


async def test_page_table_bitflip_detected_and_repaired():
    """SDC drill on the indirection layer itself: corrupt one mapped
    page's device pg_room entry. The next audit must spot the divergence
    from the last-sync mirrors, repair the table row from host canonical,
    flag the owning room with BIT_TABLE, quarantine it, and row-repair it
    from the checksummed checkpoint — then audit clean."""
    prt = PagedPlaneRuntime(PD, tick_ms=10)
    for room in range(3):
        s = prt.slots.alloc_room(f"r{room}")
        s.alloc_track("t0")
        s.alloc_sub("p0")
        s.alloc_sub("p1")
        prt.set_track(room, 0, published=True, is_video=False)
        prt.set_subscription(room, 0, 1, subscribed=True)

    def push_audio(i):
        for room in range(3):
            prt.ingest.push(PacketIn(room=room, track=0,
                                     sn=(1000 + i) & 0xFFFF, ts=960 * i,
                                     size=50, payload=b"a"))

    for i in range(2):
        push_audio(i)
        await prt.step_once()
    async with prt.state_lock:
        blob = prt.encode_snapshot(prt.snapshot())
    mon = IntegrityMonitor(prt, audit_every_ticks=4, max_row_repairs=3,
                           storm_threshold=4)
    mon.snapshot_provider = lambda: prt.decode_snapshot(blob)
    escalations: list[str] = []
    mon.escalate_cb = escalations.append
    prt.integrity = mon

    # flip a mapped page of room 1 to "free" on the DEVICE table only
    victim = int(prt.pager.pages_of_room(1)[0])
    prt.table = prt.table._replace(
        pg_room=prt.table.pg_room.at[victim].set(-1))
    assert prt.table_repairs == 0

    table_hit = False
    for i in range(2, 14):
        push_audio(i)
        await prt.step_once()
        if mon.last_mask and mon.last_mask[1] & BIT_TABLE:
            table_hit = True
    assert table_hit, "audit never flagged the table-corrupted room"
    assert prt.table_repairs >= 1
    assert mon.rows_quarantined >= 1 and mon.rows_repaired >= 1
    assert escalations == []                    # row repair, no restart
    assert sorted(mon.quarantined) == []        # released after repair
    # device table re-converged to the host canonical mirrors
    assert np.array_equal(np.asarray(prt.table.pg_room), prt.pager.pg_room)
    # and the plane keeps forwarding on the repaired layout
    push_audio(14)
    res = await prt.step_once()
    assert res.fwd_packets > 0
