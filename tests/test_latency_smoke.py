"""Slow latency smoke: a short real-socket express-lane run.

Registered behind ``python -m tools.check --latency`` (and pytest's
``slow`` marker — tier-1 excludes it): boots the 2-room interactive
shape from bench.py's wire section with the express lane enabled and
asserts the tier actually engages and stays under a deliberately loose
wire-p99 bound. The bound is a smoke detector for regressions that
re-introduce tick-queue waits on the express path (an order of
magnitude above the target measured in BASELINE.md), not a perf gate —
shared CI boxes are noisy.
"""

import pytest

from bench import wire_bench
from livekit_server_tpu.models import plane

pytestmark = pytest.mark.slow

# Loose by design: the express path's measured local p99 is ~1-2 orders
# below this; a tick-queued regression lands above it even on a busy box
# (2 ms ticks → batching alone costs ≥ a window + pipeline depth).
P99_BOUND_MS = 50.0


async def test_express_wire_p99_smoke():
    dims = plane.PlaneDims(rooms=2, tracks=8, pkts=8, subs=6)
    out = await wire_bench(
        dims,
        tick_ms=2,
        duration_s=3.0,
        warm_ticks=30,
        video_tracks=4,
        audio_tracks=4,
        low_latency=True,
        express_max_subs=dims.subs,
    )
    assert out.get("task_errors") is None or not out["task_errors"]
    assert out["express_samples"] > 0, "express tier never carried traffic"
    assert out["express"]["active_rooms"], "no room promoted to express"
    assert out["p99_wire_express_ms"] < P99_BOUND_MS, (
        f"express wire p99 {out['p99_wire_express_ms']} ms ≥ "
        f"{P99_BOUND_MS} ms — arrival-driven sends are queueing somewhere "
        f"(late causes: {out['late_cause']})"
    )
