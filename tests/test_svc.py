"""SVC + dependency-descriptor selection tests.

Reference parity: pkg/sfu/videolayerselector vp9.go / dependency-
descriptor.go behaviors — onion forwarding, keyframe-gated upswitch,
end-of-frame downswitch, decode-target switching, chain-break detection.
"""

import jax
import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import svc


def run_svc(state, pkts):
    """pkts: list of (sid, tid, kf, sw_up, eof)."""
    P = len(pkts)
    a = lambda i, dt=jnp.int32: jnp.asarray([p[i] for p in pkts], dt)
    return svc.select_tick(
        state, a(0), a(1), a(2, jnp.bool_), a(3, jnp.bool_), a(4, jnp.bool_),
        jnp.ones((P,), jnp.bool_),
    )


def test_svc_onion_forwarding():
    # One subscriber targeting spatial 1, temporal 3.
    st = svc.init_state(1, target_spatial=1, target_temporal=3)
    # Keyframe picture with layers 0..2 → locks on, forwards sid<=1 only.
    st, fwd, drp, up, nk = run_svc(
        st, [(0, 0, True, True, False), (1, 0, True, True, False), (2, 0, True, True, True)]
    )
    fwd = np.asarray(fwd)[:, 0]
    assert fwd.tolist() == [True, True, False]
    assert not bool(np.asarray(nk)[0])
    # Delta frames keep the onion flowing.
    st, fwd, _, _, _ = run_svc(
        st, [(0, 0, False, False, False), (1, 0, False, False, True), (2, 0, False, False, True)]
    )
    assert np.asarray(fwd)[:, 0].tolist() == [True, True, False]


def test_svc_upswitch_waits_for_keyframe():
    st = svc.init_state(1, target_spatial=0, target_temporal=3)
    st, fwd, *_ = run_svc(st, [(0, 0, True, True, True)])
    assert np.asarray(fwd)[0, 0]
    # Raise target to 2: delta frames keep old layer; needs keyframe.
    st = st._replace(target_spatial=jnp.asarray([2], jnp.int32))
    st, fwd, _, _, nk = run_svc(st, [(0, 0, False, False, False), (1, 0, False, False, False), (2, 0, False, False, True)])
    assert np.asarray(fwd)[:, 0].tolist() == [True, False, False]
    assert bool(np.asarray(nk)[0])
    # Keyframe arrives → full onion up to 2.
    st, fwd, _, _, nk = run_svc(st, [(0, 0, True, True, False), (1, 0, True, True, False), (2, 0, True, True, True)])
    assert np.asarray(fwd)[:, 0].tolist() == [True, True, True]
    assert not bool(np.asarray(nk)[0])


def test_svc_downswitch_at_end_of_frame():
    st = svc.init_state(1, target_spatial=2, target_temporal=3)
    st, fwd, *_ = run_svc(st, [(0, 0, True, True, False), (1, 0, True, True, False), (2, 0, True, True, True)])
    st = st._replace(target_spatial=jnp.asarray([0], jnp.int32))
    # Mid-frame packets still forward the old onion; after eof, next frame drops.
    st, fwd, *_ = run_svc(st, [(0, 0, False, False, False), (2, 0, False, False, True)])
    assert np.asarray(fwd)[:, 0].tolist() == [True, True]
    st, fwd, *_ = run_svc(st, [(0, 0, False, False, False), (2, 0, False, False, True)])
    assert np.asarray(fwd)[:, 0].tolist() == [True, False]


def test_svc_pause():
    st = svc.init_state(1, target_spatial=2)
    st, *_ = run_svc(st, [(0, 0, True, True, True)])
    st = st._replace(target_spatial=jnp.asarray([-1], jnp.int32))
    st, fwd, *_ = run_svc(st, [(0, 0, False, False, True)])
    assert not np.asarray(fwd).any()
    assert int(st.current_spatial[0]) == -1


def run_dd(state, pkts):
    """pkts: (dti_mask, switch_mask, frame, kf)."""
    P = len(pkts)
    a = lambda i, dt=jnp.int32: jnp.asarray([p[i] for p in pkts], dt)
    return svc.dd_select_tick(
        state, a(0), a(1), a(2), a(3, jnp.bool_), jnp.ones((P,), jnp.bool_)
    )


def test_dd_decode_target_selection():
    # 3 decode targets; packet needed for targets via bitmask.
    st = svc.init_dd_state(1, target_dt=2)
    # keyframe present for all targets (mask 0b111), switchable everywhere
    st, fwd, drp, broken = run_dd(st, [(0b111, 0b111, 1, True), (0b100, 0b100, 2, False), (0b001, 0b000, 3, False)])
    assert np.asarray(fwd)[:, 0].tolist() == [True, True, False]
    assert not bool(np.asarray(broken)[0])


def test_dd_switch_waits_for_indication():
    st = svc.init_dd_state(1, target_dt=0)
    st, fwd, *_ = run_dd(st, [(0b111, 0b111, 1, True)])
    st = st._replace(target_dt=jnp.asarray([2], jnp.int32))
    # no switch indication for dt2 → stays on dt0 selection
    st, fwd, drp, _ = run_dd(st, [(0b001, 0b000, 2, False), (0b100, 0b000, 3, False)])
    assert np.asarray(fwd)[:, 0].tolist() == [True, False]
    # switch point arrives
    st, fwd, _, _ = run_dd(st, [(0b100, 0b100, 4, False)])
    assert np.asarray(fwd)[0, 0]
    assert int(st.active_dt[0]) == 2


def test_dd_chain_break_detection():
    st = svc.init_dd_state(1, target_dt=0)
    st, *_ = run_dd(st, [(0b1, 0b1, 1, True)])
    # frame 2 lost; frame 3 arrives → chain broken
    st, fwd, _, broken = run_dd(st, [(0b1, 0b0, 3, False)])
    assert bool(np.asarray(broken)[0])
    # keyframe resets the chain
    st, _, _, broken = run_dd(st, [(0b1, 0b1, 9, True)])
    assert not bool(np.asarray(broken)[0])
