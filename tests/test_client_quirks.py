"""Client-quirk configuration, publication watchdog, and media-loss proxy.

Reference parity: pkg/clientconfiguration (device/SDK rules → per-client
config at join), pkg/rtc/supervisor (announced-but-never-published track
reaping), pkg/rtc/medialossproxy.go (max subscriber audio loss relayed
upstream so publishers enable Opus FEC).
"""

import asyncio
import socket

from livekit_server_tpu.clientconfig import ClientConfigurationManager
from livekit_server_tpu.models import plane
from livekit_server_tpu.protocol import decode_signal_response
from livekit_server_tpu.routing.messagechannel import MessageChannel
from livekit_server_tpu.rtc import Participant, Room
from livekit_server_tpu.runtime import PlaneRuntime

DIMS = plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4)

FIREFOX_LINUX = {"browser": "Firefox", "os": "Linux", "sdk": "js"}


def drain(sink):
    out = []
    while True:
        try:
            out.append(decode_signal_response(sink._q.get_nowait()))
        except asyncio.QueueEmpty:
            return out


def test_clientconfig_rules():
    m = ClientConfigurationManager()
    cfg = m.get_configuration(FIREFOX_LINUX)
    assert cfg is not None and "video/h264" in cfg.disabled_publish_codecs
    cfg = m.get_configuration({"browser": "firefox mobile", "os": "Android"})
    assert cfg is not None
    assert m.get_configuration({"browser": "chrome", "os": "linux"}) is None
    assert m.get_configuration({"device_model": "xiaomi 2201117ti", "os": "android"}) is not None
    assert m.get_configuration({"device_model": "xiaomi 2201117ti", "os": "ios"}) is None
    assert m.get_configuration(None) is None


async def test_quirk_blocks_h264_publish_and_rides_join():
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    try:
        room = Room("quirk", runtime)
        sink = MessageChannel(size=100)
        p = Participant("ff", room, response_sink=sink, client_info=FIREFOX_LINUX)
        room.join(p)
        assert p.client_config is not None

        # H.264 publish is rejected for this client; VP8 is fine.
        assert p.add_track_request(
            {"cid": "c1", "type": 1, "mime_type": "video/H264"}
        ) is None
        assert p.add_track_request(
            {"cid": "c2", "type": 1, "mime_type": "video/VP8"}
        ) is not None
    finally:
        await runtime.stop()


async def test_publication_watchdog_reaps_stale_pending():
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    try:
        room = Room("watchdog", runtime)
        sink = MessageChannel(size=100)
        p = Participant("pub", room, response_sink=sink)
        room.join(p)
        info = p.add_track_request({"cid": "ghost", "type": 0, "name": "mic"})
        assert info is not None and "ghost" in p.pending_tracks
        assert p.reap_stale_publications(wait_s=30.0) == []  # not stale yet
        p.pending_since["ghost"] -= 31.0
        assert p.reap_stale_publications(wait_s=30.0) == ["ghost"]
        assert "ghost" not in p.pending_tracks
        kinds = [r.kind for r in drain(sink)]
        assert "track_unpublished" in kinds
    finally:
        await runtime.stop()


async def test_media_loss_proxy_relays_max_audio_loss_upstream():
    from livekit_server_tpu.runtime.udp import RTCP_RR, build_rr, start_udp_transport
    from tests.test_native import rtp_packet

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        pub_ssrc = transport.assign_ssrc(0, 0, is_video=False)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        pub.setblocking(False)
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        # Publisher media latches its source address; egress mints the
        # downtrack SSRC the subscriber reports against.
        for i in range(3):
            pub.sendto(
                rtp_packet(sn=100 + i, ts=960 * i, ssrc=pub_ssrc,
                           payload=b"op" + bytes([i])),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress_batch(res.egress_batch)
            await asyncio.sleep(0.01)
        down_ssrc = transport.subscriber_ssrc(0, 1, 0)

        # Subscriber reports 25% loss (fraction_lost = 64/256) via RR
        # from its registered address.
        transport._handle_rtcp(
            build_rr(0xABC, down_ssrc, 64), sub.getsockname()
        )
        assert transport._down_frac_lost.get((0, 0)) == 64

        # Next SR window relays the max loss upstream to the publisher.
        transport._last_sr_ms = -10_000
        transport._send_srs(asyncio.get_event_loop().time() * 1000.0)
        await asyncio.sleep(0.05)
        got_rr = None
        while True:
            try:
                d, _ = pub.recvfrom(2048)
                if d[1] == RTCP_RR:
                    got_rr = d
            except BlockingIOError:
                break
        assert got_rr is not None, "no upstream RR reached the publisher"
        assert int.from_bytes(got_rr[8:12], "big") == pub_ssrc
        assert got_rr[12] == 64  # fraction_lost relayed
        assert transport._down_frac_lost == {}  # window reset
        pub.close()
        sub.close()
    finally:
        transport.transport.close()
        await runtime.stop()
