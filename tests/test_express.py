"""Two-tier latency plane: the express lane (runtime/express.py).

The load-bearing claim is bit-equivalence — an express room's wire
output (SN/TS/VP8 descriptor rewrites, payload bytes, marker) must be
byte-identical to what the batched tick would have produced for the
same packets against the same mirror state. The rest of the suite
pins the seams the lane must honor exactly like the batched tier:
governor shedding, integrity quarantine, migration freeze, NACK
replay, and the fast-path/slow-path subscriber split. The migration
drill at the bottom is the cross-plane version: an express room
freezes, hands off two-phase, and replays its freeze window with zero
SN loss while the source's tier state resets.
"""

import asyncio
import socket

import numpy as np
import pytest

from livekit_server_tpu.config.config import ConfigError
from livekit_server_tpu.models import plane
from livekit_server_tpu.native import rtp as parser
from livekit_server_tpu.routing import MemoryBus
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.ingest import PacketIn
from livekit_server_tpu.runtime.udp import start_udp_transport
from tests.conftest import free_port
from tests.test_migration import (
    make_cfg,
    pump_until,
    start_node,
    stop_all,
    wait_for,
)

DIMS = plane.PlaneDims(rooms=2, tracks=2, pkts=4, subs=4)


def tap_express(rt):
    """Install a sender hook that materializes every express entry into
    plain dicts (payload bytes copied out of the live slab at send time,
    exactly when a real sender would read them)."""
    out = []

    def sender(cols):
        for i in range(len(cols)):
            off, ln = int(cols.pay_off[i]), int(cols.pay_len[i])
            out.append({
                "room": int(cols.rooms[i]), "track": int(cols.tracks[i]),
                "sub": int(cols.subs[i]),
                "sn": int(cols.sn[i]) & 0xFFFF,
                "ts": int(cols.ts[i]) & 0xFFFFFFFF,
                "pid": int(cols.pid[i]), "tl0": int(cols.tl0[i]),
                "keyidx": int(cols.keyidx[i]),
                "payload": bytes(cols.slab[off:off + ln]),
                "marker": bool(cols.marker[i]),
            })
        return len(cols)

    rt.express.sender = sender
    return out


def _ekey(e: dict):
    return (e["room"], e["track"], e["sub"], e["sn"], e["ts"], e["pid"],
            e["tl0"], e["keyidx"], e["payload"], e["marker"])


def _pkey(p):
    return (p.room, p.track, p.sub, p.sn, p.ts, p.pid, p.tl0, p.keyidx,
            p.payload, p.marker)


def _push_av(rt, w: int) -> None:
    """One video (layer 2 = default target, keyframe on w=0) + one audio
    packet for window w — the same bytes on every runtime under test."""
    rt.ingest.push(PacketIn(
        room=0, track=0, sn=500 + w, ts=3000 * w, size=60,
        payload=b"vid-%d-payload" % w, marker=True, layer=2, temporal=0,
        keyframe=(w == 0), layer_sync=(w == 0), begin_pic=True,
        pid=700 + w, tl0=w, keyidx=w % 32))
    rt.ingest.push(PacketIn(
        room=0, track=1, sn=100 + w, ts=960 * w, size=20,
        payload=b"aud-%d" % w, audio_level=30))


def _setup_av(rt) -> None:
    rt.set_track(0, 0, published=True, is_video=True)
    rt.set_track(0, 1, published=True, is_video=False)
    for s in (1, 2):
        rt.set_subscription(0, 0, s, subscribed=True)
        rt.set_subscription(0, 1, s, subscribed=True)


# -- bit-equivalence ----------------------------------------------------------

async def test_express_wire_output_byte_identical_to_batched():
    """The same packet sequence through an express-tier runtime and a
    batched-only runtime must produce the identical multiset of wire
    tuples (munged SN/TS/pid/tl0/keyidx, payload bytes, marker) per
    subscriber — the decision scan, munger lanes, and payload gathering
    are one algebra in two places."""
    rt_ex = PlaneRuntime(DIMS, tick_ms=10, express_max_subs=2)
    rt_ba = PlaneRuntime(DIMS, tick_ms=10)
    _setup_av(rt_ex)
    _setup_av(rt_ba)
    ex_entries = tap_express(rt_ex)
    out_ex, out_ba = [], []
    for w in range(6):
        _push_av(rt_ex, w)
        _push_av(rt_ba, w)
        res_ex = await rt_ex.step_once()
        res_ba = await rt_ba.step_once()
        out_ex.extend(_pkey(p) for p in res_ex.egress if not p.padding)
        out_ba.extend(_pkey(p) for p in res_ba.egress if not p.padding)
    assert rt_ex.express.active[0], "room never promoted"
    assert rt_ex.express.stats["promotes"] >= 1
    assert len(ex_entries) > 0, "express tier never carried a packet"
    # Batched-runtime totals: 6 windows × 2 tracks × 2 subs.
    assert len(out_ba) == 24
    combined = sorted(out_ex + [_ekey(e) for e in ex_entries])
    assert combined == sorted(out_ba)
    # The lanes ended at the same point too (shared sequencing space).
    assert np.array_equal(rt_ex.munger.last_sn, rt_ba.munger.last_sn)


# -- promote → overload → demote continuity -----------------------------------

async def test_promote_shed_demote_audio_continuity():
    """Audio continuity 100% across the whole tier lifecycle: batched
    warm-up, promotion takeover, governor L3 shed (overload), and the
    demotion back to batched — every SN exactly once, in order, for
    every subscriber."""
    rt = PlaneRuntime(DIMS, tick_ms=10, express_max_subs=2)
    rt.set_track(0, 0, published=True, is_video=False)
    for s in (1, 2):
        rt.set_subscription(0, 0, s, subscribed=True)
    ex = tap_express(rt)
    got = {1: [], 2: []}
    express_sns = set()
    sn = 100

    async def run_windows(n):
        nonlocal sn
        for _ in range(n):
            mark = len(ex)
            rt.ingest.push(PacketIn(room=0, track=0, sn=sn, ts=0, size=10,
                                    payload=b"x"))
            res = await rt.step_once()
            for p in res.egress:
                if not p.padding and p.track == 0:
                    got[p.sub].append(p.sn)
            for e in ex[mark:]:
                got[e["sub"]].append(e["sn"])
                express_sns.add(e["sn"])
            sn += 1

    await run_windows(2)                 # batched; 2nd boundary promotes
    assert rt.express.active[0]
    # Retier is a host-side lane swap: promote, shed, and demote below
    # must never retrace the device tick (recompile watchdog).
    rt.mark_warm()
    await run_windows(3)                 # express steady state
    rt.set_shed(pause_video=True)        # overload: audio is never shed
    await run_windows(2)
    rt.set_shed(pause_video=False)
    rt.set_express_pin(0, False)         # force back to batched
    await run_windows(2)
    assert not rt.express.active[0]
    assert rt.compile_ledger.post_warmup == 0
    for s in (1, 2):
        assert got[s] == list(range(100, sn)), f"sub {s} lost or reordered"
    assert express_sns, "express tier never carried audio"
    assert rt.express.stats["promotes"] >= 1
    assert rt.express.stats["demotes"] >= 1


# -- governor seam ------------------------------------------------------------

async def test_governor_shed_mutes_express_video_audio_flows():
    """set_shed(pause_video=True) must bind on the express tier at the
    next retier exactly as it binds the batched upload: video entries
    stop, audio keeps flowing on-arrival."""
    rt = PlaneRuntime(DIMS, tick_ms=10, express_max_subs=2)
    rt.set_track(0, 0, published=True, is_video=True)
    rt.set_track(0, 1, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    rt.set_subscription(0, 1, 1, subscribed=True)
    ex = tap_express(rt)

    async def window(w):
        _push_av(rt, w)
        return await rt.step_once()

    await window(0)
    await window(1)
    assert rt.express.active[0]
    mark = len(ex)
    await window(2)
    assert {e["track"] for e in ex[mark:]} == {0, 1}
    rt.set_shed(pause_video=True)
    await rt.step_once()                 # boundary rebuilds the express base
    mark = len(ex)
    res = await window(3)
    tracks = {e["track"] for e in ex[mark:]}
    assert tracks == {1}, f"video must shed on the express tier, got {tracks}"
    # And the batched tier didn't sneak the video out either.
    assert not any(p.track == 0 and not p.padding for p in res.egress)


# -- integrity seam -----------------------------------------------------------

class _StubIntegrity:
    """The quarantine surface the runtime and lane consume, without the
    audit kernel: a mutable `quarantined` set plus the no-op hooks the
    tick loop calls."""

    def __init__(self):
        self.quarantined = set()
        self._pending_repair = set()

    def maybe_audit(self, tick_index):
        pass

    async def process(self):
        pass


async def test_quarantine_blocks_express_mid_window():
    """Quarantine lands on the worker thread mid-window; the lane's live
    check must stop on-arrival sends IMMEDIATELY — not one retier later —
    and the batched fan-out masks the room the same tick."""
    rt = PlaneRuntime(DIMS, tick_ms=10, express_max_subs=2)
    rt.integrity = _StubIntegrity()
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    ex = tap_express(rt)
    for w in range(2):
        rt.ingest.push(PacketIn(room=0, track=0, sn=100 + w, ts=0, size=10,
                                payload=b"x"))
        await rt.step_once()
    assert rt.express.active[0]
    mark = len(ex)
    rt.ingest.push(PacketIn(room=0, track=0, sn=102, ts=0, size=10,
                            payload=b"x"))
    assert len(ex) > mark, "express should be flowing pre-quarantine"

    rt.integrity.quarantined.add(0)
    mark, n0 = len(ex), rt.express.stats["express_pkts"]
    rt.ingest.push(PacketIn(room=0, track=0, sn=103, ts=0, size=10,
                            payload=b"x"))
    assert len(ex) == mark, "quarantined room must not express-send"
    assert rt.express.stats["express_pkts"] == n0
    res = await rt.step_once()
    assert not any(p.room == 0 and not p.padding for p in res.egress)

    rt.integrity.quarantined.clear()
    await rt.step_once()                 # boundary drops the quarantine mute
    mark = len(ex)
    rt.ingest.push(PacketIn(room=0, track=0, sn=104, ts=0, size=10,
                            payload=b"x"))
    assert len(ex) > mark, "express should resume after the quarantine lifts"
    await rt.step_once()


# -- migration-freeze seam + teardown -----------------------------------------

async def test_freeze_demotes_and_clear_room_resets():
    """A frozen row demotes at the next boundary (its packets route to
    the bridge sink, never the lane), re-promotion after unfreeze waits
    for a FRESH device mirror, and clear_room leaves no tier state for
    the next tenant."""
    rt = PlaneRuntime(DIMS, tick_ms=10, express_max_subs=2)
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    ex = tap_express(rt)
    for w in range(2):
        rt.ingest.push(PacketIn(room=0, track=0, sn=100 + w, ts=0, size=10,
                                payload=b"x"))
        await rt.step_once()
    assert rt.express.active[0]

    bridged = []
    rt.ingest.frozen_rows.add(0)
    rt.ingest.freeze_sinks[0] = bridged.append
    await rt.step_once()
    assert not rt.express.active[0] and not rt.express.desired[0]
    mark = len(ex)
    rt.ingest.push(PacketIn(room=0, track=0, sn=102, ts=0, size=10,
                            payload=b"x"))
    assert len(ex) == mark, "nothing may express past the freeze snapshot"
    assert len(bridged) == 1 and bridged[0].sn == 102

    rt.ingest.frozen_rows.discard(0)
    rt.ingest.freeze_sinks.pop(0)
    await rt.step_once()                 # eligible again, but mirror is stale
    assert not rt.express.active[0], "re-promotion must wait for a fresh mirror"
    await rt.step_once()
    assert rt.express.active[0]

    rt.clear_room(0)
    lane = rt.express
    assert not lane.active[0] and not lane.desired[0] and not lane.mirror_ok[0]
    assert lane.pin[0] == 0
    assert (lane.cur_sp[0] == -1).all() and (lane.tgt_sp[0] == -1).all()
    assert (lane.words[0] == 0).all() and not lane.express_subs[0].any()


# -- NACK replay --------------------------------------------------------------

async def test_nack_replay_covers_express_sends():
    """An express send must be NACK-replayable exactly like a batched
    send: the window's express log lands in the host replay ring at the
    boundary, keyed by the munged SN, payload bytes intact."""
    rt = PlaneRuntime(DIMS, tick_ms=10, express_max_subs=2)
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    ex = tap_express(rt)
    for w in range(2):
        rt.ingest.push(PacketIn(room=0, track=0, sn=100 + w, ts=0, size=10,
                                payload=b"seed"))
        await rt.step_once()
    assert rt.express.active[0]
    mark = len(ex)
    rt.ingest.push(PacketIn(room=0, track=0, sn=102, ts=0, size=12,
                            payload=b"express-pay"))
    assert len(ex) == mark + 1
    entry = ex[mark]
    await rt.step_once()                 # log → replay ring
    reps = rt.resolve_nacks(0, 1, 0, [entry["sn"]])
    assert len(reps) == 1
    assert reps[0].sn == entry["sn"]
    assert reps[0].payload == b"express-pay"


# -- fast-path / slow-path subscriber split -----------------------------------

async def test_sub_provider_splits_tiers_disjoint_and_complete():
    """Only the provider's fast-path subscribers ride the lane; the rest
    of the room's subscribers keep riding the batched tick. Union
    complete, intersection empty."""
    rt = PlaneRuntime(DIMS, tick_ms=10, express_max_subs=2)
    rt.set_track(0, 0, published=True, is_video=False)
    for s in (1, 2):
        rt.set_subscription(0, 0, s, subscribed=True)
    fast = np.zeros((DIMS.rooms, DIMS.subs), bool)
    fast[0, 1] = True
    rt.express.sub_provider = lambda: fast
    ex = tap_express(rt)
    for w in range(2):
        rt.ingest.push(PacketIn(room=0, track=0, sn=100 + w, ts=0, size=10,
                                payload=b"x"))
        await rt.step_once()
    assert rt.express.active[0]
    assert rt.express.express_subs[0, 1] and not rt.express.express_subs[0, 2]
    mark = len(ex)
    rt.ingest.push(PacketIn(room=0, track=0, sn=102, ts=0, size=10,
                            payload=b"y"))
    res = await rt.step_once()
    ex_subs = {e["sub"] for e in ex[mark:] if e["sn"] == 102}
    ba_subs = {p.sub for p in res.egress if not p.padding and p.sn == 102}
    assert ex_subs == {1} and ba_subs == {2}


# -- end to end over real UDP -------------------------------------------------

async def test_express_udp_wire_end_to_end():
    """Express sends leave through the real transport (_send_express →
    native egress_express_send, or the per-packet fallback) and arrive
    at the subscriber's socket: every SN exactly once across both tiers,
    payload bytes intact."""
    udims = plane.PlaneDims(rooms=2, tracks=4, pkts=8, subs=4)
    runtime = PlaneRuntime(udims, tick_ms=10, express_max_subs=2)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        transport.attach_express(runtime.express)
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        transport.assign_ssrc(room=0, track=0, is_video=False)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        got, batched_sns = [], []
        for i in range(6):
            runtime.ingest.push(PacketIn(room=0, track=0, sn=600 + i,
                                         ts=960 * i, size=10,
                                         payload=b"opus" + bytes([i])))
            res = await runtime.step_once()
            batched_sns.extend(p.sn for p in res.egress if not p.padding)
            transport.send_egress(res.egress)
            await asyncio.sleep(0.02)
            while True:
                try:
                    data, _ = sub.recvfrom(2048)
                    if not (192 <= data[1] <= 223):  # skip interleaved RTCP
                        got.append(data)
                except BlockingIOError:
                    break

        assert runtime.express.active[0]
        assert runtime.express.stats["express_dgrams"] >= 4
        assert len(got) == 6
        sns = []
        for data in got:
            out = parser.parse_batch(
                data, np.asarray([0], np.int32),
                np.asarray([len(data)], np.int32))[0]
            sn = int(out["sn"])
            sns.append(sn)
            off, ln = int(out["payload_off"]), int(out["payload_len"])
            assert data[off:off + ln] == b"opus" + bytes([sn - 600])
        assert sorted(sns) == [600 + i for i in range(6)]
        # The tiers never overlapped: every datagram left through exactly
        # one of them.
        assert len(batched_sns) + runtime.express.stats["express_dgrams"] == 6
    finally:
        sub.close()
        transport.transport.close()


# -- express ↔ migration ------------------------------------------------------

async def test_express_room_migrates_with_zero_loss():
    """An express-tier room freezes, hands off two-phase, and replays
    its freeze window on the target with zero SN loss — and the source's
    tier state (activation, selector mirror, sub words) resets with the
    row so nothing leaks past the snapshot."""
    bus = MemoryBus()
    a = b = None
    sub_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        a = await start_node(bus, plane={"express_max_subs": 2})
        b = await start_node(bus)
        rm_a, rm_b = a.room_manager, b.room_manager
        rt_a, rt_b = rm_a.runtime, rm_b.runtime
        assert rt_a.express is not None and rt_b.express is None

        room = await rm_a.get_or_create_room("exmig")
        row_a = room.slots.row
        rt_a.set_track(row_a, 0, published=True, is_video=False)
        rt_a.set_subscription(row_a, 0, 1, subscribed=True)
        sub_sock.bind(("127.0.0.1", 0))
        sub_sock.setblocking(False)
        rm_a.udp.register_subscriber(row_a, 1, sub_sock.getsockname())
        await wait_for(lambda: bool(rt_a.express.active[row_a]),
                       what="express promotion on the source")

        for i in range(3):
            rt_a.ingest.push(PacketIn(room=row_a, track=0, sn=100 + i, ts=0,
                                      size=10, payload=b"x"))
        await pump_until(rt_a, row_a, 102)
        assert rt_a.express.stats["express_pkts"] >= 3
        # Express munges at PUSH time, so the lane is at 102 before the
        # serving loop has drained the staging window. Wait the drain out:
        # packets still staged at freeze time would (correctly, see
        # express.py's freeze notes) be bridged and re-delivered on the
        # target — at-most-once duplicates, which this test pins to zero
        # by freezing only on an empty window.
        await wait_for(
            lambda: not bool(np.asarray(rt_a.ingest.valid[row_a]).any()),
            what="staging drain before freeze")

        got_b = []
        rt_b.on_tick(lambda res: got_b.extend(
            p.sn for p in res.egress if p.track == 0 and p.sub == 1))
        rm_b.migration.on_adopt.append(
            lambda r: rt_b.set_subscription(r.slots.row, 0, 1,
                                            subscribed=True))

        def feed_window(r):
            # Freeze-window arrivals: the row is frozen on the source, so
            # these must route to the bridge (never the lane) and replay
            # on the target.
            for i in range(3, 6):
                rt_a.ingest.push(PacketIn(room=row_a, track=0, sn=100 + i,
                                          ts=0, size=10, payload=b"w"))
        rm_b.migration.on_adopt.append(feed_window)

        assert await rm_a.migrate_room("exmig")
        row_b = rm_b.rooms["exmig"].slots.row
        await pump_until(rt_b, row_b, 105)
        await asyncio.sleep(0.05)
        assert sorted(got_b) == [103, 104, 105], \
            "freeze window lost or duplicated"
        # Source tier state fully reset with the row.
        lane = rt_a.express
        assert not lane.active.any() and not lane.desired[row_a]
        assert (lane.cur_sp[row_a] == -1).all()
        assert (lane.words[row_a] == 0).all()
        assert rt_a.ingest.frozen_rows == set()
    finally:
        sub_sock.close()
        await stop_all(a, b)


# -- config validation --------------------------------------------------------

def test_express_config_validation():
    with pytest.raises(ConfigError, match="express_max_subs"):
        make_cfg(free_port(), plane={"express_max_subs": 8})   # > subs_per_room
    with pytest.raises(ConfigError, match="express_max_subs"):
        make_cfg(free_port(), plane={"express_max_subs": -1})
    with pytest.raises(ConfigError, match="express_max_rooms"):
        make_cfg(free_port(), plane={"express_max_subs": 2,
                                     "express_max_rooms": 0})
