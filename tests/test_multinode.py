"""Multi-node over a real network bus.

Reference parity: test/multinode_test.go — N servers against one shared
Redis: cross-node room routing + signal relay, node-shutdown takeover —
plus the room-migration seeding of pkg/rtc/participant.go:823
(MaybeStartMigration), here as whole-room media-plane row handoff.

The bus is the in-repo BusServer/TCPBusClient (routing/tcpbus.py) over
real TCP sockets — NOT the in-process MemoryBus.
"""

import asyncio
import json
import socket

import aiohttp
import numpy as np

from livekit_server_tpu.config import load_config
from livekit_server_tpu.models import plane
from livekit_server_tpu.routing.tcpbus import BusServer, TCPBusClient
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.ingest import PacketIn
from livekit_server_tpu.service.server import create_server
from tests.conftest import free_port
from tests.test_service import API_KEY, API_SECRET, SignalClient, make_config


async def start_bus() -> BusServer:
    bus = BusServer()
    await bus.start("127.0.0.1", 0)
    return bus


async def start_node(bus_port: int, **cfg_overrides):
    client = await TCPBusClient.connect("127.0.0.1", bus_port)
    srv = create_server(make_config(free_port(), **cfg_overrides), bus=client)
    await srv.start()
    return srv, client


async def test_tcpbus_kv_and_pubsub():
    """The bus speaks the MessageBus protocol over real sockets: state
    written by one client is visible to another, and pub/sub (including
    patterns) fans out across connections."""
    bus = await start_bus()
    try:
        a = await TCPBusClient.connect("127.0.0.1", bus.port)
        b = await TCPBusClient.connect("127.0.0.1", bus.port)

        await a.hset("nodes", "n1", "one")
        assert await b.hget("nodes", "n1") == "one"
        assert await b.hgetall("nodes") == {"n1": "one"}
        await b.hdel("nodes", "n1")
        assert await a.hget("nodes", "n1") is None

        await a.set("k", "v", None)
        assert await b.get("k") == "v"
        assert await b.setnx("k", "other", None) is False
        await b.delete("k")
        assert await a.setnx("k", "other", None) is True

        sub = b.subscribe("room:*")
        exact = b.subscribe("room:one")
        n = await a.publish("room:one", "hello")
        assert n == 2
        assert await sub.read(timeout=2) == "hello"
        assert await exact.read(timeout=2) == "hello"
        sub.close()
        await asyncio.sleep(0.05)
        assert await a.publish("room:two", "x") == 0  # exact sub doesn't match
        await a.close()
        await b.close()
    finally:
        bus.close()


async def test_cross_node_session_over_tcp_bus():
    """Two servers, one bus: a room pinned to node A serves a participant
    whose WebSocket terminates on node B — the signal stream relays over
    the TCP bus (redisrouter signal relay, multinode_test.go)."""
    bus = await start_bus()
    srv_a = srv_b = None
    try:
        srv_a, _ = await start_node(bus.port)
        srv_b, _ = await start_node(bus.port)
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, srv_a.port)
            await alice.connect("shared", "alice")
            # Room is now pinned to node A.
            assert (
                await srv_b.router.get_node_for_room("shared")
                == srv_a.router.local_node.node_id
            )
            bob = SignalClient(s, srv_b.port)
            join_b = await bob.connect("shared", "bob")
            # Bob's session actually lives on node A (relayed).
            assert join_b["participant"]["identity"] == "bob"
            others = [p["identity"] for p in join_b["other_participants"]]
            assert "alice" in others
            assert "shared" in srv_a.room_manager.rooms
            assert "shared" not in srv_b.room_manager.rooms
            # Cross-node signal round trip: bob's state update reaches the
            # room on A and fans back out to alice's socket on A.
            deadline = asyncio.get_event_loop().time() + 5
            seen_bob = False
            while not seen_bob and asyncio.get_event_loop().time() < deadline:
                seen_bob = any(
                    p.get("identity") == "bob"
                    for m in alice.signals
                    for p in m.get("update", {}).get("participants", [])
                )
                await asyncio.sleep(0.05)
            assert seen_bob, f"no bob update in {alice.signals}"
            await alice.close()
            await bob.close()
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                await srv.stop(force=True)
        bus.close()


async def test_dead_node_takeover():
    """Node A dies with a room pinned to it; a client hitting node B gets
    the room re-homed there instead of a dead relay (RemoveDeadNodes +
    the multinode shutdown-reconnect flow)."""
    bus = await start_bus()
    srv_a = srv_b = None
    try:
        srv_a, bus_a = await start_node(bus.port)
        srv_b, _ = await start_node(bus.port)
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, srv_a.port)
            await alice.connect("takeover", "alice")
            await alice.close()
            a_id = srv_a.router.local_node.node_id
            # Crash A: heartbeat stops and it vanishes from the registry
            # (what the dead-node reaper does after staleness) but its room
            # pin is left behind — a graceful stop would have cleaned it,
            # a crash doesn't.
            srv_a.router._stats_task.cancel()
            srv_a.router._session_task.cancel()
            util = await TCPBusClient.connect("127.0.0.1", bus.port)
            await util.hdel("nodes", a_id)
            await util.close()
            # The pin still names the dead node…
            assert await srv_b.router.get_node_for_room("takeover") == a_id
            # …but a join through B re-homes the room locally.
            bob = SignalClient(s, srv_b.port)
            join = await bob.connect("takeover", "bob")
            assert join["participant"]["identity"] == "bob"
            assert "takeover" in srv_b.room_manager.rooms
            assert (
                await srv_b.router.get_node_for_room("takeover")
                == srv_b.router.local_node.node_id
            )
            await bob.close()
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                await srv.stop(force=True)
        bus.close()


async def test_room_migration_snapshot_continuity():
    """Row-level handoff: media flows through node A's plane, the room
    migrates, and the SAME stream continued on node B emits contiguous
    munged SNs — the forwarder-state seeding of participant.go:823, at
    whole-room granularity."""
    dims = plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4)
    rt_a = PlaneRuntime(dims, tick_ms=10)
    rt_b = PlaneRuntime(dims, tick_ms=10)

    rt_a.set_track(0, 0, published=True, is_video=False)
    rt_a.set_subscription(0, 0, 1, subscribed=True)
    got_a = []
    for i in range(5):
        rt_a.ingest.push(PacketIn(room=0, track=0, sn=7000 + i, ts=960 * i,
                                  size=50, payload=b"a"))
        res = await rt_a.step_once()
        got_a += [p.sn for p in res.egress if p.sub == 1]
    assert got_a == list(range(7000, 7005))

    # Handoff A → B into a DIFFERENT row (row identity is node-local).
    snap = rt_a.snapshot_room(0)
    payload = PlaneRuntime.encode_room_snapshot(snap)
    rt_b.restore_room(1, PlaneRuntime.decode_room_snapshot(payload))

    # Track metadata migrated with the snapshot, but subscription masks
    # deliberately did NOT (a restored mask on a re-allocated sub column
    # would leak media) — the rejoining subscriber re-subscribes and its
    # munger lane resumes where it left off.
    rt_b.set_subscription(1, 0, 1, subscribed=True)
    got_b = []
    for i in range(5, 10):
        rt_b.ingest.push(PacketIn(room=1, track=0, sn=7000 + i, ts=960 * i,
                                  size=50, payload=b"b"))
        res = await rt_b.step_once()
        got_b += [p.sn for p in res.egress if p.sub == 1 and p.room == 1]
    assert got_b == list(range(7005, 7010))


async def test_room_handoff_over_bus():
    """Manager-level handoff: node A publishes the room snapshot to the
    bus and unpins; node B's get_or_create_room adopts it.

    Round-2 recorded a rare INVALID_ARGUMENT flake here. Round-3
    investigation: the snapshot-vs-donated-step discipline was audited —
    every self.state reader/writer (snapshot_room, restore_room, the test
    itself) holds state_lock, and the serving loop holds it across the
    donated device dispatch, so no donated buffer is reachable while a
    step is in flight; 16 consecutive runs under 3-4x synthetic CPU load
    did not reproduce. The round-2 environment had six stray synthetic-
    load processes running since its own flake testing (since killed),
    matching the 'extreme starvation' precondition. Treat any recurrence
    as a new bug with its own traceback, not a known shrug."""
    bus = await start_bus()
    srv_a = srv_b = None
    try:
        srv_a, _ = await start_node(bus.port)
        srv_b, _ = await start_node(bus.port)
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, srv_a.port)
            await alice.connect("mig", "alice")
            row_a = srv_a.room_manager.rooms["mig"].slots.row
            # Put distinctive state into the room row (munger offsets).
            rt = srv_a.room_manager.runtime
            rt.set_track(row_a, 0, published=True, is_video=False)
            rt.set_subscription(row_a, 0, 1, subscribed=True)
            for i in range(3):
                rt.ingest.push(PacketIn(room=row_a, track=0, sn=100 + i,
                                        ts=0, size=10, payload=b"x"))
            # The node's serving loop is running, so step_once() would race
            # its deferred fan-out (and now raises); let the loop drain the
            # pushed packets and wait for the munger lane to advance.
            for _ in range(500):
                if int(rt.munger.last_sn[row_a, 0, 1]) == 102:
                    break
                await asyncio.sleep(0.01)
            assert int(rt.munger.last_sn[row_a, 0, 1]) == 102
            await alice.close()

            assert await srv_a.room_manager.handoff_room("mig")
            assert "mig" not in srv_a.room_manager.rooms

            room_b = await srv_b.room_manager.get_or_create_room("mig")
            rt_b = srv_b.room_manager.runtime
            # Munger state for (track 0, sub 1) migrated: last outgoing SN
            # survives the hop (host-side state since the round-5 split).
            last_sn = int(rt_b.munger.last_sn[room_b.slots.row, 0, 1])
            assert last_sn == 102
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                await srv.stop(force=True)
        bus.close()


async def test_two_phase_migration_under_load_over_bus():
    """The migration plane's tentpole drill over real TCP sockets: audio
    flows while the room migrates A → B through the two-phase handoff.
    Every pushed SN egresses exactly once — packets landing in the freeze
    window are bridged to the target, not dropped — and the munger lane
    continues contiguously on the target (no stream reset)."""
    bus = await start_bus()
    srv_a = srv_b = None
    try:
        # Deep per-tick packet slots: under full-suite CPU load a 10ms
        # tick can stretch past several pump periods, and the default 4
        # slots per (room, track) would capacity-drop legitimate audio
        # with no migration involved at all.
        srv_a, _ = await start_node(bus.port, pkts_per_track=16)
        srv_b, _ = await start_node(bus.port, pkts_per_track=16)
        rm_a, rm_b = srv_a.room_manager, srv_b.room_manager
        rt_a, rt_b = rm_a.runtime, rm_b.runtime
        assert rm_a.migration is not None and rm_b.migration is not None

        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, srv_a.port)
            await alice.connect("live", "alice")
            row_a = rm_a.rooms["live"].slots.row
            rt_a.set_track(row_a, 0, published=True, is_video=False)
            rt_a.set_subscription(row_a, 0, 1, subscribed=True)

            got: list[int] = []   # audio SNs egressed to sub 1, either node

            def collect(res):
                got.extend(
                    p.sn for p in res.egress if p.track == 0 and p.sub == 1
                )

            rt_a.on_tick(collect)
            rt_b.on_tick(collect)
            # Subscription masks don't travel; the adopting node re-arms
            # the listener (stand-in for the client's reconnect).
            rm_b.migration.on_adopt.append(
                lambda r: rt_b.set_subscription(
                    r.slots.row, 0, 1, subscribed=True
                )
            )

            stop = asyncio.Event()
            sent: list[int] = []

            async def pump():
                sn = 500
                while not stop.is_set():
                    for rm in (rm_a, rm_b):
                        room = rm.rooms.get("live")
                        if room is not None:
                            rm.runtime.ingest.push(PacketIn(
                                room=room.slots.row, track=0, sn=sn,
                                ts=960 * (sn - 500), size=40, payload=b"s",
                            ))
                            sent.append(sn)
                            sn += 1
                            break
                    await asyncio.sleep(0.004)

            pump_task = asyncio.ensure_future(pump())
            await asyncio.sleep(0.3)               # media flowing on A
            assert await rm_a.migrate_room("live")
            assert "live" not in rm_a.rooms and "live" in rm_b.rooms
            assert (
                await srv_a.router.get_node_for_room("live")
                == srv_b.router.local_node.node_id
            )
            await asyncio.sleep(0.3)               # media flowing on B
            stop.set()
            await pump_task
            await asyncio.sleep(0.2)               # drain the last ticks

            # 100% audio continuity across the cutover: every pushed SN
            # egressed exactly once — none dropped in the freeze window,
            # none duplicated by the bridge replay. (Set equality, not
            # order: a bridged straggler may share a tick with a direct
            # push on the target.)
            assert sorted(got) == sent, (
                f"lost={sorted(set(sent) - set(got))[:10]} "
                f"dup={sorted(sn for sn in set(got) if got.count(sn) > 1)[:10]}"
            )
            assert len(got) > 60, "pump never reached the plane"
            # The lane continued — target's last SN is the last one sent.
            row_b = rm_b.rooms["live"].slots.row
            assert int(rt_b.munger.last_sn[row_b, 0, 1]) == sent[-1]
            st = rm_a.migration.stats
            assert st["commits"] == 1 and st["rollbacks"] == 0
            await alice.close()
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                await srv.stop(force=True)
        bus.close()


def make_fleet_config(port: int, extra: dict | None = None):
    """Drill-speed fleet timings. The no-overlap inequalities hold at
    scale: fence_grace 0.5 ≤ 2×lease_ttl 0.8 and 0.5 < lease_ttl 0.8 +
    failover_interval 0.4 — a dark node mutes (~0.7 s) strictly before
    the earliest takeover can finish (~1.2 s)."""
    doc = {
        "keys": {API_KEY: API_SECRET},
        "port": port,
        "bind_addresses": ["127.0.0.1"],
        "plane": {"rooms": 4, "tracks_per_room": 4, "pkts_per_track": 16,
                  "subs_per_room": 4, "tick_ms": 10},
        "rtc": {"udp_port": port + 1, "tcp_port": port + 2},
        "room": {"empty_timeout_s": 60},
        "kv": {"lease_ttl_s": 0.8, "failover_interval_s": 0.4,
               "stats_interval_s": 0.2},
        "fleet": {"fence_grace_s": 0.5, "restore_lock_ttl_s": 2.0},
        "supervisor": {"checkpoint_interval_s": 0.2},
    }
    for section, values in (extra or {}).items():
        doc[section] = {**doc.get(section, {}), **values}
    return load_config(yaml_text=json.dumps(doc))


async def start_fleet_node(bus_port: int, extra: dict | None = None):
    client = await TCPBusClient.connect("127.0.0.1", bus_port)
    srv = create_server(make_fleet_config(free_port(), extra=extra), bus=client)
    await srv.start()
    return srv, client


async def _wait_for(cond, timeout: float, what: str) -> None:
    deadline = asyncio.get_event_loop().time() + timeout
    while not cond():
        assert asyncio.get_event_loop().time() < deadline, f"timed out: {what}"
        await asyncio.sleep(0.02)


async def test_split_brain_fences_minority_and_takeover_wins():
    """The fleet plane's tentpole drill: a 2|1 bus partition darks node A
    while its room keeps producing media. The minority self-fences (wire
    mute engages while the plane is still producing — the shadow SNs
    prove the mute is load-bearing), the majority completes an elected
    takeover strictly after the mute, and the heal ends with exactly one
    owner, ZERO duplicate wire packets, and A's stale checkpoint write
    rejected by the epoch CAS."""
    bus = await start_bus()
    srv_a = srv_b = None
    try:
        srv_a, _ = await start_fleet_node(bus.port)
        srv_b, _ = await start_fleet_node(bus.port)
        rm_a, rm_b = srv_a.room_manager, srv_b.room_manager
        rt_a, rt_b = rm_a.runtime, rm_b.runtime
        a_id = srv_a.router.local_node.node_id
        b_id = srv_b.router.local_node.node_id
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, srv_a.port)
            await alice.connect("sb", "alice")
            await alice.close()
            row_a = rm_a.rooms["sb"].slots.row
            rt_a.set_track(row_a, 0, published=True, is_video=False)
            rt_a.set_subscription(row_a, 0, 1, subscribed=True)

            got: list[int] = []      # wire-visible egress (fence-gated)
            shadow: list[int] = []   # produced by A's plane WHILE fenced

            def collect_a(res):
                sns = [p.sn for p in res.egress
                       if p.track == 0 and p.sub == 1]
                # Mirror the wire gate: a fenced tick's egress never
                # reaches a socket (_dispatch_tick mute), and residual
                # packets draining after the replica closed have no
                # row→room mapping left to route them by.
                wire_visible = not rm_a.fleet.fenced and "sb" in rm_a.rooms
                (got if wire_visible else shadow).extend(sns)

            rt_a.on_tick(collect_a)
            rt_b.on_tick(
                lambda res: got.extend(
                    p.sn for p in res.egress if p.track == 0 and p.sub == 1
                )
            )

            stop = asyncio.Event()
            sent: list[int] = []

            async def pump():
                sn = 500
                while not stop.is_set():
                    pushed = False
                    # Push the SAME SN into EVERY replica: while both
                    # nodes hold the room, only the fence keeps the wire
                    # duplicate-free.
                    for rm in (rm_a, rm_b):
                        room = rm.rooms.get("sb")
                        if room is not None:
                            rm.runtime.ingest.push(PacketIn(
                                room=room.slots.row, track=0, sn=sn,
                                ts=960 * (sn - 500), size=40, payload=b"s",
                            ))
                            pushed = True
                    if pushed:
                        sent.append(sn)
                        sn += 1
                    await asyncio.sleep(0.004)

            pump_task = asyncio.ensure_future(pump())
            await asyncio.sleep(0.5)          # media + a checkpoint on A

            bus.set_partition([[b_id], [a_id]])
            # Minority goes silent on its own, within fence_grace (+ one
            # lease beat + scheduling slop).
            await _wait_for(lambda: rm_a.fleet.fenced, 3.0, "A never fenced")
            assert "fenced" in (rm_a._admission_denied("room") or "")
            # Majority elects itself and restores from A's checkpoint —
            # strictly AFTER the mute (the no-overlap timeline).
            await _wait_for(lambda: "sb" in rm_b.rooms, 6.0, "no takeover")
            assert rm_a.fleet.fenced, "takeover finished before the mute"
            rt_b.set_subscription(rm_b.rooms["sb"].slots.row, 0, 1,
                                  subscribed=True)
            await asyncio.sleep(0.3)          # dual-replica window

            bus.heal_partition()
            # A's next good lease triggers reconcile: the stale checkpoint
            # write loses its epoch CAS, which closes A's replica, and
            # only then does A unfence.
            await _wait_for(
                lambda: not rm_a.fleet.fenced and "sb" not in rm_a.rooms,
                5.0, "A never reconciled",
            )
            await asyncio.sleep(0.2)
            stop.set()
            await pump_task
            await asyncio.sleep(0.2)          # drain the last ticks

            # ZERO duplicate wire packets across partition + heal…
            dup = sorted(sn for sn in set(got) if got.count(sn) > 1)
            assert not dup, f"duplicate wire SNs: {dup[:10]}"
            # …and not because A went idle: its plane kept producing
            # wire-bound egress that ONLY the fence suppressed.
            assert shadow, "A's plane never produced while fenced"
            assert set(shadow) & set(got), "no suppressed would-be dup"
            # Stale owner's post-heal checkpoint write rejected by CAS.
            assert rm_a.fleet.fence.stats["writes_fenced"] >= 1
            assert rm_a.fleet.stats == {
                **rm_a.fleet.stats, "fences": 1, "recoveries": 1,
                "rooms_lost": 1,
            }
            assert rm_a.fleet.stats["muted_ticks"] > 0
            # Exactly one owner at a strictly higher epoch.
            epoch, holder = await rm_b.fleet.fence.read("sb")
            assert holder == b_id and epoch >= 2
            assert await srv_b.router.get_node_for_room("sb") == b_id
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                await srv.stop(force=True)
        bus.close()


async def test_node_kill_elected_failover_restores_every_room():
    """Node-kill drill: A dies holding two rooms while two survivors
    race the same dead-pin scan. The create-lock + epoch-CAS election
    gives every room exactly one restorer, and the media room comes back
    with 100% audio continuity (every pushed SN egresses exactly once,
    lane contiguous across the failover)."""
    bus = await start_bus()
    srvs: list = [None, None, None]
    try:
        for i in range(3):
            srvs[i], _ = await start_fleet_node(bus.port)
        srv_a, srv_b, srv_c = srvs
        rm_a, rm_b, rm_c = (s.room_manager for s in srvs)
        rt_a = rm_a.runtime
        async with aiohttp.ClientSession() as s:
            for room_name in ("k1", "k2"):
                cl = SignalClient(s, srv_a.port)
                await cl.connect(room_name, "pub")
                await cl.close()
            row_a = rm_a.rooms["k1"].slots.row
            rt_a.set_track(row_a, 0, published=True, is_video=False)
            rt_a.set_subscription(row_a, 0, 1, subscribed=True)

            got: list[int] = []
            for rm in (rm_a, rm_b, rm_c):
                rm.runtime.on_tick(
                    lambda res: got.extend(
                        p.sn for p in res.egress
                        if p.track == 0 and p.sub == 1
                    )
                )
            # Subscriptions never travel in a snapshot (restore_room
            # clears the masks — a restored bit on a re-allocated sub
            # column would leak media), so model the subscriber re-attach
            # the way production does: re-subscribe at adoption time,
            # before the room is visible to ingest.
            for rm in (rm_b, rm_c):
                rm.on_adopt.append(
                    (lambda rm_: lambda room: (
                        rm_.runtime.set_subscription(
                            room.slots.row, 0, 1, subscribed=True
                        ) if room.name == "k1" else None
                    ))(rm)
                )

            live = [rm_a, rm_b, rm_c]
            stop = asyncio.Event()
            sent: list[int] = []

            async def pump():
                sn = 900
                while not stop.is_set():
                    for rm in list(live):
                        room = rm.rooms.get("k1")
                        if room is not None:
                            rm.runtime.ingest.push(PacketIn(
                                room=room.slots.row, track=0, sn=sn,
                                ts=960 * (sn - 900), size=40, payload=b"s",
                            ))
                            sent.append(sn)
                            sn += 1
                            break
                    await asyncio.sleep(0.004)

            pump_task = asyncio.ensure_future(pump())
            await _wait_for(lambda: len(sent) >= 20, 10.0,
                            "pump never reached A")
            # Quiesce the pump and let A's lane drain, then force a fresh
            # checkpoint so the survivors restore the full lane.
            live.remove(rm_a)
            await _wait_for(
                lambda: not sent
                or int(rt_a.munger.last_sn[row_a, 0, 1]) == sent[-1],
                3.0, "A's lane never drained",
            )
            await rm_a.checkpoint_rooms()
            # Crash A: heartbeat and session relay stop; the lease lapses
            # on its own. (A's plane keeps running — its later checkpoint
            # writes must LOSE the epoch CAS once a survivor claims.)
            srv_a.router._stats_task.cancel()
            srv_a.router._session_task.cancel()

            def owners(name):
                return [rm for rm in (rm_b, rm_c) if name in rm.rooms]

            # Generous window: on a loaded shared-CPU rig a single XLA
            # compile can stall the loop 15-20 s, which once ate the whole
            # wait — the failover itself completes in ~1.2 s when the loop
            # is scheduled.
            await _wait_for(
                lambda: owners("k1") and owners("k2"), 45.0,
                "rooms never failed over",
            )
            assert len(owners("k1")) == 1 and len(owners("k2")) == 1
            winner = owners("k1")[0]
            pumped_to_a = len(sent)
            await _wait_for(lambda: len(sent) >= pumped_to_a + 20, 10.0,
                            "pump never reached the winner")
            stop.set()
            await pump_task
            row_w = winner.rooms["k1"].slots.row
            await _wait_for(
                lambda: int(winner.runtime.munger.last_sn[row_w, 0, 1])
                == sent[-1],
                3.0, "winner's lane never drained",
            )
            await asyncio.sleep(0.1)   # let the last tick's fan-out land

            # 100% audio continuity: every pushed SN egressed exactly once.
            assert sorted(got) == sent, (
                f"lost={sorted(set(sent) - set(got))[:10]} "
                f"dup={sorted(sn for sn in set(got) if got.count(sn) > 1)[:10]}"
            )
            assert len(got) >= 40, "pump never reached the plane"
            # Exactly one elected restorer per room across the fleet.
            restored = sum(
                rm.fleet.orchestrator.stats["restored"] for rm in (rm_b, rm_c)
            )
            assert restored == 2
            for name in ("k1", "k2"):
                epoch, holder = await rm_b.fleet.fence.read(name)
                assert holder == owners(name)[0].fleet.fence.node_id
                assert epoch >= 2
    finally:
        for srv in srvs:
            if srv is not None:
                await srv.stop(force=True)
        bus.close()


async def test_rebalancer_sheds_hot_node_with_continuity():
    """Load-aware rebalancing rides the migration plane: the node holding
    every room sheds its emptiest one to the idle peer, and media in the
    moved room survives the hop with every SN egressing exactly once."""
    extra = {"fleet": {
        "rebalance_enabled": True, "rebalance_interval_s": 0.3,
        "rebalance_headroom": 0.25, "rebalance_max_moves": 1,
    }}
    bus = await start_bus()
    srv_a = srv_b = None
    try:
        srv_a, _ = await start_fleet_node(bus.port, extra=extra)
        srv_b, _ = await start_fleet_node(bus.port, extra=extra)
        rm_a, rm_b = srv_a.room_manager, srv_b.room_manager
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, srv_a.port)
            await alice.connect("keep", "alice")     # stays connected
            bob = SignalClient(s, srv_a.port)
            await bob.connect("mover", "bob")
            await bob.close()                        # mover: 0 participants
            row_a = rm_a.rooms["mover"].slots.row
            rm_a.runtime.set_track(row_a, 0, published=True, is_video=False)
            rm_a.runtime.set_subscription(row_a, 0, 1, subscribed=True)
            rm_b.migration.on_adopt.append(
                lambda r: rm_b.runtime.set_subscription(
                    r.slots.row, 0, 1, subscribed=True
                )
            )

            got: list[int] = []
            for rm in (rm_a, rm_b):
                rm.runtime.on_tick(
                    lambda res: got.extend(
                        p.sn for p in res.egress
                        if p.track == 0 and p.sub == 1
                    )
                )
            stop = asyncio.Event()
            sent: list[int] = []

            async def pump():
                sn = 300
                while not stop.is_set():
                    for rm in (rm_a, rm_b):
                        room = rm.rooms.get("mover")
                        if room is not None:
                            rm.runtime.ingest.push(PacketIn(
                                room=room.slots.row, track=0, sn=sn,
                                ts=960 * (sn - 300), size=40, payload=b"s",
                            ))
                            sent.append(sn)
                            sn += 1
                            break
                    await asyncio.sleep(0.004)

            pump_task = asyncio.ensure_future(pump())
            # The rebalancer picks the emptiest room on the hottest node:
            # "mover" (0 participants) leaves, "keep" (alice) stays.
            # Moved = adopted on B (PREPARE) and released on A (COMMIT
            # resolution) — the source replica lives until the commit.
            await _wait_for(
                lambda: "mover" in rm_b.rooms and "mover" not in rm_a.rooms,
                20.0, "no rebalance",
            )
            assert "keep" in rm_a.rooms
            moved_at = len(sent)
            await _wait_for(lambda: len(sent) >= moved_at + 20, 10.0,
                            "pump never reached the target")
            stop.set()
            await pump_task
            row_b = rm_b.rooms["mover"].slots.row
            await _wait_for(
                lambda: int(rm_b.runtime.munger.last_sn[row_b, 0, 1])
                == sent[-1],
                3.0, "target's lane never drained",
            )
            await asyncio.sleep(0.1)   # let the last tick's fan-out land

            assert sorted(got) == sent, (
                f"lost={sorted(set(sent) - set(got))[:10]} "
                f"dup={sorted(sn for sn in set(got) if got.count(sn) > 1)[:10]}"
            )
            assert rm_a.fleet.rebalancer.stats["moves"] >= 1
            assert rm_a.migration.stats["commits"] >= 1
            epoch, holder = await rm_b.fleet.fence.read("mover")
            assert holder == srv_b.router.local_node.node_id and epoch >= 2
            await alice.close()
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                await srv.stop(force=True)
        bus.close()


async def test_stale_commit_after_heal_dropped_by_epoch_guard():
    """Migration under partition: an asymmetric A→B link holds the
    PREPARE in flight, the source times out and rolls back, and the heal
    delivers the whole stale handshake late — the target adopts, obeys
    the late ABORT, and a COMMIT naming the dead epoch is dropped by the
    epoch guard. Exactly one node serves the room throughout."""
    extra = {"migration": {
        "ack_timeout_s": 0.3, "retry_attempts": 1,
        "retry_backoff_base_s": 0.05, "adopt_ttl_s": 1.0,
    }}
    bus = await start_bus()
    srv_a = srv_b = None
    try:
        srv_a, cl_a = await start_fleet_node(bus.port, extra=extra)
        srv_b, _ = await start_fleet_node(bus.port, extra=extra)
        rm_a, rm_b = srv_a.room_manager, srv_b.room_manager
        a_id = srv_a.router.local_node.node_id
        b_id = srv_b.router.local_node.node_id
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, srv_a.port)
            await alice.connect("part", "alice")
            await alice.close()

            # One-way link failure: A's pushes to B are held (not lost).
            # KV still works both ways, so leases stay healthy — this is
            # a migration-plane partition, not a node death.
            bus.set_partition([], asym_pairs=[(a_id, b_id)])
            assert not await rm_a.migration.migrate_room("part", b_id)
            assert "part" in rm_a.rooms        # rolled back, still source
            stale_epoch = rm_a.migration._epoch

            bus.heal_partition()
            # The held PREPARE adopts on B, the held ABORT (or the adopt
            # reaper) releases it again — transient, never an owner.
            await _wait_for(
                lambda: rm_b.migration.stats["adoptions"] >= 1, 5.0,
                "late PREPARE never adopted",
            )
            await _wait_for(
                lambda: "part" not in rm_b.rooms
                and not rm_b.migration._adoptions,
                5.0, "late adoption never released",
            )
            # The COMMIT from the timed-out attempt finally arrives —
            # naming a dead epoch. The guard drops it instead of
            # finalizing a handoff the source already rolled back.
            before = rm_b.migration.stats["stale_commits"]
            await cl_a.publish(
                f"node_migrate:{b_id}",
                {"kind": "commit", "room": "part", "epoch": stale_epoch},
            )
            await _wait_for(
                lambda: rm_b.migration.stats["stale_commits"] > before,
                3.0, "stale COMMIT not counted",
            )
            assert "part" not in rm_b.rooms
            # Exactly one owner the whole way: pin and epoch still name A.
            assert "part" in rm_a.rooms
            assert await srv_b.router.get_node_for_room("part") == a_id
            _epoch, holder = await rm_a.fleet.fence.read("part")
            assert holder == a_id
            assert rm_a.migration.stats["rollbacks"] >= 1
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                await srv.stop(force=True)
        bus.close()


async def test_bus_auth():
    """A token-bearing bus is the Redis-AUTH seat: unauthenticated clients
    are refused every op (the bus carries room pins and signal relay, so
    open access is cluster takeover), tokened clients work normally."""
    bus = BusServer(token="s3cret")
    await bus.start("127.0.0.1", 0)
    try:
        intruder = await TCPBusClient.connect("127.0.0.1", bus.port)
        try:
            await intruder.hset("room_node_map", "victim", "evil-node")
            raise AssertionError("unauthenticated op accepted")
        except (RuntimeError, ConnectionError):
            pass  # refused (and the connection is dropped)

        member = await TCPBusClient.connect("127.0.0.1", bus.port, token="s3cret")
        await member.hset("nodes", "n1", "one")
        assert await member.hget("nodes", "n1") == "one"
        assert await member.hget("room_node_map", "victim") is None
        await member.close()
    finally:
        bus.close()


async def test_roomservice_ops_against_non_hosting_node():
    """Admin RPCs hit node B for a room hosted on node A and are relayed
    to the hosting node over the bus (multinode_roomservice_test.go)."""
    from livekit_server_tpu.auth import AccessToken, VideoGrant
    from tests.test_service import API_KEY, API_SECRET

    bus = await start_bus()
    srv_a = srv_b = None
    try:
        srv_a, _ = await start_node(bus.port)
        srv_b, _ = await start_node(bus.port)
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, srv_a.port)
            await alice.connect("hosted-on-a", "alice")
            assert "hosted-on-a" in srv_a.room_manager.rooms

            t = AccessToken(API_KEY, API_SECRET)
            t.grant = VideoGrant(room_admin=True, room="hosted-on-a")
            hdr = {"Authorization": f"Bearer {t.to_jwt()}"}
            base_b = f"http://127.0.0.1:{srv_b.port}/twirp/livekit.RoomService"

            # List participants via the NON-hosting node.
            async with s.post(
                f"{base_b}/ListParticipants", json={"room": "hosted-on-a"},
                headers=hdr,
            ) as r:
                assert r.status == 200, await r.text()
                parts = (await r.json())["participants"]
                assert [p["identity"] for p in parts] == ["alice"]

            # Mutate metadata via the non-hosting node; the hosting node's
            # room object changes and alice gets the update.
            async with s.post(
                f"{base_b}/UpdateRoomMetadata",
                json={"room": "hosted-on-a", "metadata": "via-node-b"},
                headers=hdr,
            ) as r:
                assert r.status == 200, await r.text()
            assert srv_a.room_manager.rooms["hosted-on-a"].info.metadata == "via-node-b"

            # Remove alice via the non-hosting node.
            async with s.post(
                f"{base_b}/RemoveParticipant",
                json={"room": "hosted-on-a", "identity": "alice"},
                headers=hdr,
            ) as r:
                assert r.status == 200, await r.text()
            deadline = asyncio.get_event_loop().time() + 3
            while (
                (room_a := srv_a.room_manager.rooms.get("hosted-on-a")) is not None
                and "alice" in room_a.participants
                and asyncio.get_event_loop().time() < deadline
            ):
                await asyncio.sleep(0.05)
            assert room_a is None or "alice" not in room_a.participants
            await alice.close()
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                await srv.stop(force=True)
        bus.close()


async def test_bus_client_reconnects_and_resubscribes():
    """A dropped bus connection must not sever the node permanently (the
    go-redis auto-reconnect seat): calls fail during the outage, then
    succeed again, and live subscriptions are re-issued on the fresh
    connection."""
    bus = await start_bus()
    port = bus.port
    try:
        client = await TCPBusClient.connect("127.0.0.1", port)
        other = await TCPBusClient.connect("127.0.0.1", port)
        sub = client.subscribe("announce")
        await client.set("k", "v1")
        await asyncio.sleep(0.05)

        # Sever the client's connection out from under it (network blip).
        client._writer.transport.abort()
        deadline = asyncio.get_event_loop().time() + 3
        while client.reconnects == 0:
            assert asyncio.get_event_loop().time() < deadline, "no reconnect"
            await asyncio.sleep(0.05)
        assert await client.get("k") == "v1"          # calls work again
        await asyncio.sleep(0.05)                      # re-sub settles
        await other.publish("announce", {"hello": 1})  # pushes flow again
        msg = await sub.read(timeout=3)
        assert msg == {"hello": 1}

        # Full bus-process restart on the same port: state is fresh (like
        # a flushed Redis) but the client recovers without intervention.
        bus.close()
        client._writer.transport.abort()
        other._writer.transport.abort()
        await asyncio.sleep(0.1)
        bus2 = BusServer()
        await bus2.start("127.0.0.1", port)
        try:
            deadline = asyncio.get_event_loop().time() + 5
            while True:
                try:
                    await client.set("k2", "v2")
                    break
                except ConnectionError:
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(0.1)
            assert await client.get("k2") == "v2"
            await client.close()
            await other.close()
        finally:
            bus2.close()
    finally:
        bus.close()

async def test_bus_client_survives_malformed_frame():
    """A malformed frame (bad JSON, or a frame with neither 'p' nor 'i')
    means the stream is desynced: the client must treat it as connection
    loss and reconnect — not die with _connected=True, which would hang
    every pending and future call forever."""
    import json as _json

    bus = await start_bus()
    try:
        client = await TCPBusClient.connect("127.0.0.1", bus.port)
        await client.set("k", "v1")

        def inject(raw: bytes) -> None:
            client._reader.feed_data(len(raw).to_bytes(4, "big") + raw)

        # Structurally invalid frame: valid JSON lacking both 'p' and 'i'.
        inject(_json.dumps({"x": 1}).encode())
        deadline = asyncio.get_event_loop().time() + 3
        while client.reconnects == 0:
            assert asyncio.get_event_loop().time() < deadline, (
                "malformed frame killed the reader without reconnecting"
            )
            await asyncio.sleep(0.05)
        assert not client.closed
        assert await client.get("k") == "v1"

        # Byte-garbage frame (json.JSONDecodeError path), on the fresh
        # connection this time.
        inject(b"\xff not json \xff")
        deadline = asyncio.get_event_loop().time() + 3
        while client.reconnects < 2:
            assert asyncio.get_event_loop().time() < deadline, "no 2nd reconnect"
            await asyncio.sleep(0.05)
        assert await client.get("k") == "v1"
        await client.close()
    finally:
        bus.close()


async def test_egress_records_from_dead_node_reaped():
    """Lifecycle reaper (redisstore.go:67-944 cleanup-worker seat): an
    egress whose worker/node dies mid-job must not stay ACTIVE in every
    node's aggregator forever — it goes FAILED after the stale window and
    expires after the ended TTL, so ListEgress stays clean cluster-wide."""
    import json as _json
    import time as _time

    from livekit_server_tpu.service.egress import EgressStatus

    bus = await start_bus()
    try:
        srv_a, cl_a = await start_node(bus.port)
        srv_b, cl_b = await start_node(bus.port)
        try:
            # A worker (lived on some third node) reports an ACTIVE egress;
            # both aggregators adopt it.
            info = {
                "egress_id": "EG_dead", "room_name": "r", "kind": "track",
                "status": int(EgressStatus.ACTIVE), "started_at": 0,
                "ended_at": 0, "error": "", "request": {},
            }
            await cl_a.publish("egress_updates", _json.dumps(info))
            await asyncio.sleep(0.1)
            assert "EG_dead" in srv_a.ioinfo.egresses
            assert "EG_dead" in srv_b.ioinfo.egresses

            # The worker's node dies (no further updates). After the stale
            # window the record is failed...
            now = _time.monotonic()
            for srv in (srv_a, srv_b):
                srv.ioinfo.reap(now + srv.ioinfo.STALE_ACTIVE_S + 1)
                rec = srv.ioinfo.egresses["EG_dead"]
                assert rec.status == EgressStatus.FAILED
                assert "lost" in rec.error
            # ...and after the ended TTL it is gone from every List.
            for srv in (srv_a, srv_b):
                srv.ioinfo.reap(
                    _time.monotonic() + srv.ioinfo.ENDED_TTL_S + 1
                )
                assert "EG_dead" not in srv.ioinfo.egresses
        finally:
            await srv_a.stop()
            await srv_b.stop()
            await cl_a.close()
            await cl_b.close()
    finally:
        bus.close()
