"""HostMunger (runtime/munge.py) vs the golden scan formulations.

ops.rtpmunger / ops.vp8 define the munging semantics (and remain the
device-checkpointable spec, tested by test_rtpmunger.py / test_vp8.py).
The production rewrite path runs host-side since the round-5
decide-on-device/rewrite-on-host split — these tests pin the two
implementations bit-identical on randomized multi-tick streams, including
switches, drops, padding, and migration snapshot/restore.
"""

import jax
import numpy as np

from livekit_server_tpu.models import plane
from livekit_server_tpu.ops import rtpmunger, vp8
from livekit_server_tpu.runtime.munge import HostMunger


def _random_tick(rng, R, T, K, S):
    sn = rng.integers(0, 1 << 16, (R, T, K))
    ts = rng.integers(0, 1 << 32, (R, T, K))
    pid = rng.integers(0, 1 << 15, (R, T, K))
    tl0 = rng.integers(0, 256, (R, T, K))
    ki = rng.integers(0, 32, (R, T, K))
    begin = rng.random((R, T, K)) < 0.5
    valid = rng.random((R, T, K)) < 0.85
    ts_jump = np.where(rng.random((R, T, K)) < 0.3, -1, 3000)
    fwd = rng.random((R, T, K, S)) < 0.6
    drop = (rng.random((R, T, K, S)) < 0.2) & ~fwd
    switch = (rng.random((R, T, K, S)) < 0.15) & fwd
    return sn, ts, ts_jump, pid, tl0, ki, begin, valid, fwd, drop, switch


def _ops_reference(ticks, R, T, K, S):
    """Run the same stream through the jax scan modules (vmapped R×T)."""
    tile = lambda tree: jax.tree.map(  # noqa: E731
        lambda x: np.broadcast_to(np.asarray(x), (R, T) + x.shape).copy(), tree
    )
    mstate = rtpmunger.MungerState(*tile(rtpmunger.init_state(S)))
    vstate = vp8.VP8State(*tile(vp8.init_state(S)))
    munge = jax.jit(jax.vmap(jax.vmap(rtpmunger.munge_tick)))
    vmunge = jax.jit(jax.vmap(jax.vmap(vp8.munge_tick)))
    outs = []
    for (sn, ts, ts_jump, pid, tl0, ki, begin, valid, fwd, drop, switch) in ticks:
        i32 = lambda x: np.asarray(x, np.int64).astype(np.uint32).astype(np.int64).astype(np.int32, casting="unsafe")  # noqa: E731
        mstate, out_sn, out_ts, send = munge(
            mstate, i32(sn), i32(ts), valid, fwd, drop, switch, i32(ts_jump)
        )
        vstate, out_pid, out_tl0, out_ki = vmunge(
            vstate, i32(pid), i32(tl0), i32(ki), begin, valid, fwd, drop, switch
        )
        outs.append((
            np.asarray(send),
            np.asarray(out_sn) & 0xFFFF,
            np.asarray(out_ts).astype(np.int64) & 0xFFFFFFFF,
            np.asarray(out_pid) & 0x7FFF,
            np.asarray(out_tl0) & 0xFF,
            np.asarray(out_ki) & 0x1F,
        ))
    return mstate, vstate, outs


def test_host_munger_matches_ops_scans():
    R, T, K, S = 2, 3, 4, 5
    rng = np.random.default_rng(42)
    ticks = [_random_tick(rng, R, T, K, S) for _ in range(6)]

    mstate, vstate, ref = _ops_reference(ticks, R, T, K, S)

    host = HostMunger(plane.PlaneDims(R, T, K, S))
    for tick, (send_ref, r_sn, r_ts, r_pid, r_tl0, r_ki) in zip(ticks, ref):
        sn, ts, ts_jump, pid, tl0, ki, begin, valid, fwd, drop, switch = tick
        h_sn, h_ts, h_pid, h_tl0, h_ki = host.apply_dense(
            sn, ts, ts_jump, pid, tl0, ki, begin, valid, fwd, drop, switch
        )
        send = fwd & valid[..., None]
        assert (send == send_ref).all()
        np.testing.assert_array_equal(h_sn[send], r_sn[send])
        np.testing.assert_array_equal(h_ts[send], r_ts[send])
        np.testing.assert_array_equal(h_pid[send], r_pid[send])
        np.testing.assert_array_equal(h_tl0[send], r_tl0[send])
        np.testing.assert_array_equal(h_ki[send], r_ki[send])

    # Final state agrees too (migration seeds from this).
    np.testing.assert_array_equal(
        host.sn_offset, np.asarray(mstate.sn_offset).astype(np.int64) & 0xFFFF
    )
    np.testing.assert_array_equal(
        host.last_sn, np.asarray(mstate.last_sn).astype(np.int64) & 0xFFFF
    )
    np.testing.assert_array_equal(host.started, np.asarray(mstate.started))
    np.testing.assert_array_equal(
        host.last_ts, np.asarray(mstate.last_ts).astype(np.int64) & 0xFFFFFFFF
    )
    np.testing.assert_array_equal(
        host.pid_offset, np.asarray(vstate.pid_offset).astype(np.int64) & 0x7FFF
    )
    np.testing.assert_array_equal(host.v_started, np.asarray(vstate.started))


def test_host_padding_matches_ops_padding_tick():
    R, T, K, S = 1, 2, 3, 4
    rng = np.random.default_rng(7)
    host = HostMunger(plane.PlaneDims(R, T, K, S))
    # Start lanes with one forwarded tick.
    tick = _random_tick(rng, R, T, K, S)
    sn, ts, ts_jump, pid, tl0, ki, begin, valid, fwd, drop, switch = tick
    valid[:] = True
    fwd[:] = True
    drop[:] = False
    switch[:] = False
    host.apply_dense(sn, ts, ts_jump, pid, tl0, ki, begin, valid, fwd, drop, switch)

    before_sn = host.last_sn.copy()
    before_off = host.sn_offset.copy()
    pad_num = np.zeros((R, S), np.int32)
    pad_track = np.full((R, S), -1, np.int32)
    pad_num[0, 1] = 3
    pad_track[0, 1] = 1
    pads = host.padding(pad_num, pad_track, ts_advance=900)
    assert len(pads) == 3
    sns = [p[3] for p in pads]
    assert sns == [
        (int(before_sn[0, 1, 1]) + j + 1) & 0xFFFF for j in range(3)
    ]
    # SN space advanced: offset -= n, last_sn += n (rtpmunger.go padding).
    assert host.sn_offset[0, 1, 1] == (before_off[0, 1, 1] - 3) & 0xFFFF
    assert host.last_sn[0, 1, 1] == (before_sn[0, 1, 1] + 3) & 0xFFFF
    # Other lanes untouched.
    assert (host.last_sn[0, 0] == before_sn[0, 0]).all()


def test_native_walk_matches_numpy_dense():
    """The C++ walker (native/munge.cpp) must be bit-identical to the
    numpy spec, including state evolution across ticks."""
    from livekit_server_tpu import native

    if native.munge is None:
        import pytest

        pytest.skip("native munge walker unavailable (no toolchain)")
    R, T, K, S = 2, 3, 4, 37  # S > 32: exercises the multi-word mask path
    dims = plane.PlaneDims(R, T, K, S)
    rng = np.random.default_rng(11)
    h_np = HostMunger(dims)
    h_cc = HostMunger(dims)
    import jax.numpy as jnp

    from livekit_server_tpu.models.plane import _pack_bits

    for i in range(5):
        sn, ts, ts_jump, pid, tl0, ki, begin, valid, fwd, drop, switch = (
            _random_tick(rng, R, T, K, S)
        )
        # Device contract: send ⊆ valid (selection folds validity in).
        fwd &= valid[..., None]
        drop &= valid[..., None] & ~fwd
        switch &= fwd
        bits = [
            np.asarray(_pack_bits(jnp.asarray(m))) for m in (fwd, drop, switch)
        ]
        # numpy lane: the spec path, bypassing the native walker.
        o = h_np.apply_dense(sn, ts, ts_jump, pid, tl0, ki, begin, valid,
                             fwd, drop, switch)
        rr, tt, kk, ss = np.nonzero(fwd)
        cols_cc = native.munge.walk(
            sn, ts, ts_jump, pid, tl0, ki, begin, valid,
            *bits, h_cc, cap=int(fwd.sum()),
        )
        assert cols_cc is not None
        np.testing.assert_array_equal(cols_cc[0], rr)
        np.testing.assert_array_equal(cols_cc[1], tt)
        np.testing.assert_array_equal(cols_cc[2], kk)
        np.testing.assert_array_equal(cols_cc[3], ss)
        np.testing.assert_array_equal(
            cols_cc[4], o[0][rr, tt, kk, ss].astype(np.int32))
        np.testing.assert_array_equal(
            cols_cc[5].view(np.uint32).astype(np.int64),
            o[1][rr, tt, kk, ss] & 0xFFFFFFFF)
        np.testing.assert_array_equal(
            cols_cc[6], o[2][rr, tt, kk, ss].astype(np.int32))
        np.testing.assert_array_equal(
            cols_cc[7], o[3][rr, tt, kk, ss].astype(np.int32))
        np.testing.assert_array_equal(
            cols_cc[8], o[4][rr, tt, kk, ss].astype(np.int32))
    # State evolved identically through five ticks.
    for f in HostMunger.FIELDS:
        np.testing.assert_array_equal(
            getattr(h_np, f), getattr(h_cc, f), err_msg=f
        )


def test_host_munger_snapshot_roundtrip():
    R, T, K, S = 2, 2, 2, 3
    rng = np.random.default_rng(3)
    host = HostMunger(plane.PlaneDims(R, T, K, S))
    for i in range(3):
        host.apply_dense(*_random_tick(rng, R, T, K, S))
    snap = host.snapshot_room(1)
    other = HostMunger(plane.PlaneDims(R, T, K, S))
    other.restore_room(0, snap)
    np.testing.assert_array_equal(other.last_sn[0], host.last_sn[1])
    np.testing.assert_array_equal(other.ts_offset[0], host.ts_offset[1])
    np.testing.assert_array_equal(other.started[0], host.started[1])
    np.testing.assert_array_equal(other.pid_offset[0], host.pid_offset[1])
