"""Live migration plane: two-phase handoff, rollback, and node drain.

Clusters here share one in-process MemoryBus (create_server(cfg, bus=...))
— the TCP-bus variant of the handoff lives in test_multinode.py. The
chaos drills drive the seeded fault seams (config.faults.mig_*) and
assert the invariants the plane exists for: a failed handoff leaves the
room serving on the source with zero audio loss, no row leaks on either
side, and no epoch ever double-commits.
"""

import asyncio
import json

from livekit_server_tpu.config import load_config
from livekit_server_tpu.routing import MemoryBus
from livekit_server_tpu.runtime.ingest import PacketIn
from livekit_server_tpu.service.server import create_server
from tests.conftest import free_port
from tests.test_service import API_KEY, API_SECRET


def make_cfg(port: int, **overrides):
    base = {
        "keys": {API_KEY: API_SECRET},
        "port": port,
        "bind_addresses": ["127.0.0.1"],
        "plane": {"rooms": 4, "tracks_per_room": 4, "pkts_per_track": 4,
                  "subs_per_room": 4, "tick_ms": 10},
        # Rooms in these tests are created admin-style (never joined);
        # keep the idle reaper out of the way.
        "room": {"empty_timeout_s": 60},
        "rtc": {"udp_port": port + 1, "tcp_port": port + 2},
        "migration": {"ack_timeout_s": 0.3, "retry_attempts": 2,
                      "retry_backoff_base_s": 0.02,
                      "retry_backoff_max_s": 0.05, "adopt_ttl_s": 2.0},
    }
    for key, val in overrides.items():
        base[key] = ({**base[key], **val}
                     if isinstance(base.get(key), dict) else val)
    return load_config(yaml_text=json.dumps(base))


async def start_node(bus, **overrides):
    srv = create_server(make_cfg(free_port(), **overrides), bus=bus)
    await srv.start()
    return srv


async def stop_all(*servers):
    for srv in servers:
        if srv is not None:
            await srv.stop(force=True)


def rows_used(srv) -> int:
    return srv.room_manager.runtime.slots.rooms_used


async def wait_for(cond, timeout=3.0, what="condition"):
    """Poll for an async-settling assertion (abort/commit handlers on the
    peer run as spawned tasks after the caller's await returns)."""
    deadline = asyncio.get_event_loop().time() + timeout
    while not cond():
        assert asyncio.get_event_loop().time() < deadline, f"timed out: {what}"
        await asyncio.sleep(0.01)


async def pump_until(rt, row, sn, timeout=5.0):
    """Wait for the serving loop to advance the munger lane to `sn`."""
    deadline = asyncio.get_event_loop().time() + timeout
    while int(rt.munger.last_sn[row, 0, 1]) != sn:
        assert asyncio.get_event_loop().time() < deadline, (
            f"lane stuck at {int(rt.munger.last_sn[row, 0, 1])}, want {sn}"
        )
        await asyncio.sleep(0.01)


# -- happy path --------------------------------------------------------------

async def test_two_phase_commit_moves_room_and_state():
    """PREPARE → ACK → COMMIT: the room moves, the pin moves, munger
    offsets survive, the source row is released, and freeze-window
    packets bridged to the target egress there — no audio SN lost or
    duplicated across the cutover."""
    bus = MemoryBus()
    a = b = None
    try:
        a = await start_node(bus)
        b = await start_node(bus)
        rm_a, rm_b = a.room_manager, b.room_manager
        rt_a, rt_b = rm_a.runtime, rm_b.runtime
        assert rm_a.migration is not None and rm_b.migration is not None

        room = await rm_a.get_or_create_room("mig")
        row_a = room.slots.row
        rt_a.set_track(row_a, 0, published=True, is_video=False)
        rt_a.set_subscription(row_a, 0, 1, subscribed=True)
        sent = []
        for i in range(3):
            rt_a.ingest.push(PacketIn(room=row_a, track=0, sn=100 + i, ts=0,
                                      size=10, payload=b"x"))
            sent.append(100 + i)
        await pump_until(rt_a, row_a, 102)

        # Target egress collector + post-adoption re-subscribe (sub masks
        # deliberately don't travel; the real path is clients rejoining).
        got_b = []
        rt_b.on_tick(lambda res: got_b.extend(
            p.sn for p in res.egress if p.track == 0 and p.sub == 1))
        rm_b.migration.on_adopt.append(
            lambda r: rt_b.set_subscription(r.slots.row, 0, 1, subscribed=True))

        # Freeze-window packets: captured after the snapshot, bridged over.
        def feed_window(r):
            for i in range(3, 6):
                rt_a.ingest.push(PacketIn(room=row_a, track=0, sn=100 + i,
                                          ts=0, size=10, payload=b"w"))
                sent.append(100 + i)
        rm_b.migration.on_adopt.append(feed_window)

        assert await rm_a.migrate_room("mig")
        assert "mig" not in rm_a.rooms and "mig" in rm_b.rooms
        assert rows_used(a) == 0 and rows_used(b) == 1
        assert rt_a.ingest.frozen_rows == set()
        assert (await a.router.get_node_for_room("mig")
                == b.router.local_node.node_id)

        row_b = rm_b.rooms["mig"].slots.row
        # Munger lane continued (105 after the bridged window drains).
        await pump_until(rt_b, row_b, 105)
        await asyncio.sleep(0.05)
        assert sorted(got_b) == sent[3:], "bridged window lost or duplicated"
        st_a, st_b = rm_a.migration.stats, rm_b.migration.stats
        assert st_a["commits"] == 1 and st_a["rollbacks"] == 0
        assert st_b["adoptions"] == 1 and st_b["commits_in"] == 1
        assert st_b["bridged_in"] == 3

        # Recompile watchdog (GC11 drill): the adoption restore and the
        # bridged-window drain pay their compiles above; steady post-
        # migration ticks on the adopting node must not retrace.
        rt_b.mark_warm()
        for i in range(6, 9):
            rt_b.ingest.push(PacketIn(room=row_b, track=0, sn=100 + i,
                                      ts=0, size=10, payload=b"s"))
        await pump_until(rt_b, row_b, 108)
        assert rt_b.compile_ledger.post_warmup == 0
    finally:
        await stop_all(a, b)


# -- chaos drills ------------------------------------------------------------

async def test_silent_target_rolls_back_with_no_leaks():
    """Drill: the target adopts every PREPARE then goes silent (killed
    mid-handoff). Every attempt times out, the source rolls back and
    keeps serving with zero audio gap, and the target's aborted
    adoptions release their rows — no leak, no double-serving."""
    bus = MemoryBus()
    a = b = None
    try:
        a = await start_node(bus)
        b = await start_node(
            bus, faults={"enabled": True, "mig_drop_prepare": True})
        rm_a, rm_b = a.room_manager, b.room_manager
        rt_a = rm_a.runtime

        await rm_a.get_or_create_room("mig")
        row_a = rm_a.rooms["mig"].slots.row
        rt_a.set_track(row_a, 0, published=True, is_video=False)
        rt_a.set_subscription(row_a, 0, 1, subscribed=True)
        for i in range(3):
            rt_a.ingest.push(PacketIn(room=row_a, track=0, sn=200 + i, ts=0,
                                      size=10, payload=b"x"))
        await pump_until(rt_a, row_a, 202)

        assert not await rm_a.migrate_room("mig")

        # Source: still serving, unfrozen, pin still ours.
        assert "mig" in rm_a.rooms
        assert rt_a.ingest.frozen_rows == set()
        assert (await a.router.get_node_for_room("mig")
                == a.router.local_node.node_id)
        st = rm_a.migration.stats
        assert st["rollbacks"] == 1 and st["commits"] == 0
        assert st["timeouts"] == 2          # retry_attempts
        # Target: every aborted adoption releases, nothing left behind
        # (the final abort is handled asynchronously — wait for it).
        stb = rm_b.migration.stats
        assert rm_b.fault.stats.mig_prepares_swallowed == 2
        await wait_for(lambda: "mig" not in rm_b.rooms, what="target release")
        assert rows_used(b) == 0
        assert stb["adoptions_released"] == stb["adoptions"]
        # 100% audio continuity on the source across the aborted handoff:
        # packets pushed now still advance the same lane contiguously.
        for i in range(3, 6):
            rt_a.ingest.push(PacketIn(room=row_a, track=0, sn=200 + i, ts=0,
                                      size=10, payload=b"x"))
        await pump_until(rt_a, row_a, 205)
    finally:
        await stop_all(a, b)


async def test_freeze_window_replays_locally_on_rollback():
    """Packets ingested during a failed handoff's freeze window are not
    lost: rollback replays them into the local ingest and they egress on
    the source in order."""
    bus = MemoryBus()
    a = b = None
    try:
        a = await start_node(bus)
        b = await start_node(
            bus, faults={"enabled": True, "mig_drop_prepare": True},
            migration={"retry_attempts": 1})
        rm_a = a.room_manager
        rt_a = rm_a.runtime
        await rm_a.get_or_create_room("mig")
        row_a = rm_a.rooms["mig"].slots.row
        rt_a.set_track(row_a, 0, published=True, is_video=False)
        rt_a.set_subscription(row_a, 0, 1, subscribed=True)
        rt_a.ingest.push(PacketIn(room=row_a, track=0, sn=300, ts=0,
                                  size=10, payload=b"x"))
        await pump_until(rt_a, row_a, 300)

        # Inject mid-freeze traffic the moment the target adopts (the
        # window between snapshot and the timeout verdict).
        def feed(r):
            for i in range(1, 4):
                rt_a.ingest.push(PacketIn(room=row_a, track=0, sn=300 + i,
                                          ts=0, size=10, payload=b"w"))
        b.room_manager.migration.on_adopt.append(feed)

        assert not await rm_a.migrate_room("mig")
        # The frozen-window packets re-entered the local plane: the lane
        # reaches 303 with no gap and no duplicate delivery.
        await pump_until(rt_a, row_a, 303)
        assert rt_a.ingest.frozen_rows == set()
    finally:
        await stop_all(a, b)


async def test_nack_renegotiates_to_next_candidate():
    """Governed admission: a draining candidate NACKs the PREPARE and the
    source renegotiates with the next ranked node — the room lands on the
    healthy peer, untouched by the refusing one."""
    bus = MemoryBus()
    a = b = c = None
    try:
        a = await start_node(bus)
        b = await start_node(bus)
        c = await start_node(bus)
        rm_a = a.room_manager
        b.room_manager.migration.draining = True   # admission-refusing peer

        await rm_a.get_or_create_room("mig")
        mig = rm_a.migration
        b_id = b.router.local_node.node_id
        c_id = c.router.local_node.node_id

        async def ranked():
            return [b_id, c_id]   # force the refusing node first

        mig._candidates = ranked
        assert await mig.migrate_room("mig")
        assert "mig" in c.room_manager.rooms
        assert "mig" not in b.room_manager.rooms and rows_used(b) == 0
        assert mig.stats["nacks_received"] == 1
        assert mig.stats["rollbacks"] == 1 and mig.stats["commits"] == 1
        assert b.room_manager.migration.stats["nacks_sent"] == 1
        assert (await a.router.get_node_for_room("mig") == c_id)
    finally:
        await stop_all(a, b, c)


async def test_late_ack_hits_epoch_guard():
    """Drill: the target delays its ACK past the source's timeout. The
    source aborts that epoch and gives up; when the stale ACK finally
    lands it finds no live attempt and is dropped — it must never
    resurrect an aborted handoff (double-commit guard)."""
    bus = MemoryBus()
    a = b = None
    try:
        a = await start_node(bus, migration={"retry_attempts": 1,
                                             "ack_timeout_s": 0.2})
        b = await start_node(
            bus, faults={"enabled": True, "mig_ack_delay_s": 0.6})
        rm_a, rm_b = a.room_manager, b.room_manager
        await rm_a.get_or_create_room("mig")

        assert not await rm_a.migrate_room("mig")
        assert "mig" in rm_a.rooms

        # Let the delayed ACK arrive and the abort settle on the target.
        await asyncio.sleep(0.8)
        assert rm_a.migration.stats["stale_acks"] == 1
        assert "mig" not in rm_b.rooms and rows_used(b) == 0
        assert rm_a.migration._attempts == {}
        assert rm_b.migration._adoptions == {}
        assert rm_b.fault.stats.mig_acks_delayed == 1
    finally:
        await stop_all(a, b)


async def test_corrupt_handoff_payload_is_nacked():
    """Drill: the encoded snapshot is damaged in flight. The target's
    checksum verification rejects it with a NACK — nothing is adopted
    from a payload that cannot prove integrity — and the source rolls
    back to serving."""
    bus = MemoryBus()
    a = b = None
    try:
        a = await start_node(
            bus, faults={"enabled": True, "mig_corrupt_handoff": True},
            migration={"retry_attempts": 1})
        b = await start_node(bus)
        rm_a, rm_b = a.room_manager, b.room_manager
        await rm_a.get_or_create_room("mig")

        assert not await rm_a.migrate_room("mig")
        assert "mig" in rm_a.rooms
        assert "mig" not in rm_b.rooms and rows_used(b) == 0
        assert rm_a.migration.stats["nacks_received"] == 1
        assert rm_b.migration.stats["adoptions"] == 0
        assert rm_a.fault.stats.mig_handoffs_corrupted == 1
    finally:
        await stop_all(a, b)


async def test_sever_mid_commit_rolls_back_then_succeeds():
    """Drill: the bus dies between the target's ACK and the source's
    COMMIT. The commit fails, the source rolls back (re-asserting its own
    pin — the repin may already have happened) and keeps serving; the
    orphaned adoption on the target is released; a later attempt, with
    the partition healed, commits cleanly."""
    bus = MemoryBus()
    a = b = None
    try:
        a = await start_node(
            bus, faults={"enabled": True, "mig_sever_handoffs": 1},
            migration={"retry_attempts": 1})
        b = await start_node(bus)
        rm_a, rm_b = a.room_manager, b.room_manager
        await rm_a.get_or_create_room("mig")
        a_id = a.router.local_node.node_id

        assert not await rm_a.migrate_room("mig")
        assert "mig" in rm_a.rooms
        assert await a.router.get_node_for_room("mig") == a_id
        assert rm_a.migration.stats["rollbacks"] == 1
        assert rm_a.fault.stats.mig_commits_severed == 1
        # The target's adoption was aborted — row released, no leak.
        await wait_for(lambda: "mig" not in rm_b.rooms, what="target release")
        assert rows_used(b) == 0

        # Partition healed (the seam consumed its budget): clean commit.
        assert await rm_a.migrate_room("mig")
        assert "mig" in rm_b.rooms and rows_used(a) == 0
        assert (await a.router.get_node_for_room("mig")
                == b.router.local_node.node_id)
    finally:
        await stop_all(a, b)


# -- node drain --------------------------------------------------------------

async def test_drain_moves_every_room_off_and_rejects_admissions():
    """Node drain: every room migrates off the draining node (bounded
    concurrency), all stay live on the survivors, the drained node holds
    zero rooms and refuses new admissions, and its quiescing plane is
    exempt from the watchdog."""
    bus = MemoryBus()
    a = b = c = None
    try:
        a = await start_node(bus)
        b = await start_node(bus)
        c = await start_node(bus)
        rm_a = a.room_manager
        names = [f"room-{i}" for i in range(3)]
        for n in names:
            await rm_a.get_or_create_room(n)
        assert rows_used(a) == 3

        summary = await rm_a.migration.drain_node()
        assert summary == {"rooms": 3, "migrated": 3, "failed": []}
        assert rm_a.rooms == {} and rows_used(a) == 0
        # Every room is live on exactly one survivor, pins updated.
        for n in names:
            owner = await a.router.get_node_for_room(n)
            assert owner in (b.router.local_node.node_id,
                             c.router.local_node.node_id)
            hosting = [s for s in (b, c) if n in s.room_manager.rooms]
            assert len(hosting) == 1
            assert hosting[0].router.local_node.node_id == owner
        # The drained node: SHUTTING_DOWN, admissions refused through BOTH
        # gates (orchestrator flag + governor drain hold), watchdog off.
        from livekit_server_tpu.routing.node import NodeState
        from livekit_server_tpu.runtime.governor import L_MAX

        assert a.router.local_node.state == NodeState.SHUTTING_DOWN
        assert rm_a._admission_denied("room") == "node draining"
        if rm_a.governor is not None:
            assert rm_a.governor.drain_hold
            assert rm_a.governor.level == L_MAX
        if rm_a.supervisor is not None:
            assert rm_a.supervisor.draining
        # A drain message over the bus is idempotent.
        assert (await rm_a.migration.drain_node()) == {"already_draining": True}
    finally:
        await stop_all(a, b, c)


async def test_drain_with_no_peers_fails_soft():
    """A lone node drains into nobody: every room stays, the summary says
    so, and the node still refuses admissions — stop() then tears the
    rooms down normally."""
    bus = MemoryBus()
    a = None
    try:
        a = await start_node(bus)
        rm = a.room_manager
        await rm.get_or_create_room("stuck")
        summary = await rm.migration.drain_node()
        assert summary["rooms"] == 1 and summary["migrated"] == 0
        assert summary["failed"] == ["stuck"]
        assert "stuck" in rm.rooms
        assert rm._admission_denied("room") == "node draining"
    finally:
        await stop_all(a)


# -- the legacy bus handoff's durability gate (satellite) --------------------

async def test_handoff_room_survives_bus_failure():
    """The fire-and-forget handoff must never tear down a room whose
    snapshot did not durably land: with the bus set failing, the room
    keeps serving on the source, unfrozen."""
    bus = MemoryBus()
    a = None
    try:
        a = await start_node(bus)
        rm = a.room_manager
        await rm.get_or_create_room("keep")
        row = rm.rooms["keep"].slots.row

        async def broken_set(key, value, ttl=None):
            raise ConnectionError("bus down")

        orig_set = bus.set
        bus.set = broken_set
        try:
            assert not await rm.handoff_room("keep")
        finally:
            bus.set = orig_set
        assert "keep" in rm.rooms
        assert row not in rm.runtime.ingest.frozen_rows
    finally:
        await stop_all(a)


async def test_adopted_room_solicits_keyframes():
    """The NACK blind-window satellite: adopting a room with published
    video tracks fires an immediate PLI per video track (audio tracks are
    left alone), so decoders resync without waiting for the replay ring
    to repopulate."""
    bus = MemoryBus()
    a = b = None
    try:
        a = await start_node(bus)
        b = await start_node(bus)
        rm_a, rm_b = a.room_manager, b.room_manager
        room = await rm_a.get_or_create_room("video")
        row_a = room.slots.row
        rm_a.runtime.set_track(row_a, 0, published=True, is_video=True)
        rm_a.runtime.set_track(row_a, 1, published=True, is_video=False)

        assert await rm_a.migrate_room("video")
        adopted = rm_b.rooms["video"]
        # The immediate solicitation recorded its throttle stamp for the
        # video col only; the audio col was never touched.
        assert 0 in adopted._last_pli and 1 not in adopted._last_pli
        # A republish clears the throttle and re-requests (the resync
        # hook registered on adoption).
        assert adopted.on_track_published
    finally:
        await stop_all(a, b)
