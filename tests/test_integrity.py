"""State-integrity plane: checksum codec units, audit-kernel rules,
seeded end-to-end bitflip chaos (detect → quarantine → row repair),
repair-storm escalation to a supervisor restart, and checkpoint
generation fallback on corruption."""

from __future__ import annotations

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime import (
    FaultInjector,
    PlaneRuntime,
    PlaneSupervisor,
)
from livekit_server_tpu.runtime.faultinject import FaultSpec, _replace_leaf
from livekit_server_tpu.runtime.ingest import PacketIn
from livekit_server_tpu.runtime.integrity import (
    AUDIT_RULES,
    BIT_BOUNDS,
    BIT_CTRL,
    BIT_CURSOR,
    BIT_NONFINITE,
    BIT_RANGE,
    IntegrityMonitor,
    audit_plane,
    init_mirror,
)
from livekit_server_tpu.utils import checksum
from livekit_server_tpu.utils.backoff import BackoffPolicy
from livekit_server_tpu.utils.checksum import ChecksumError


def make_rt(rooms: int = 3) -> PlaneRuntime:
    """Small plane with one published audio track + one subscriber per
    room (audio-only keeps selector rows inert, so injected corruption
    there persists until the audit sees it)."""
    dims = plane.PlaneDims(rooms=rooms, tracks=4, pkts=4, subs=4)
    rt = PlaneRuntime(dims, tick_ms=10)
    for room in range(rooms):
        rt.set_track(room, 0, published=True, is_video=False)
        rt.set_subscription(room, 0, 1, subscribed=True)
    return rt


def push_audio(rt: PlaneRuntime, rooms, i: int) -> None:
    for room in rooms:
        rt.ingest.push(PacketIn(room=room, track=0, sn=(1000 + i) & 0xFFFF,
                                ts=960 * i, size=50, payload=b"a"))


def poison(rt: PlaneRuntime, path: str, room: int, value) -> None:
    """Overwrite one room's row of a device-state leaf in place — the
    hand-rolled corruption the audit rules are unit-tested against."""
    leaf = rt.state
    for part in path.split("."):
        leaf = getattr(leaf, part)
    rt.state = _replace_leaf(rt.state, path, leaf.at[room].set(value))


def audit_once(rt: PlaneRuntime):
    mask, counts, _ = audit_plane(rt.state, init_mirror(rt.state))
    return np.asarray(mask), np.asarray(counts)


async def until(cond, timeout: float = 60.0, msg: str = "condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        assert asyncio.get_running_loop().time() < deadline, \
            f"timed out waiting for {msg}"
        await asyncio.sleep(0.01)


# -- checksum codec ----------------------------------------------------------

def test_frame_roundtrip():
    payload = b"media-plane checkpoint bytes" * 7
    frame = checksum.encode_frame(payload)
    assert frame[:4] == checksum.MAGIC
    assert len(frame) == checksum.HEADER_SIZE + len(payload)
    assert checksum.decode_frame(frame) == payload
    assert checksum.decode_frame_b64(checksum.encode_frame_b64(payload)) == payload


def test_frame_tamper_detected():
    fails0 = checksum.CodecStats.verify_failures
    flipped = bytearray(checksum.encode_frame(b"x" * 100))
    flipped[checksum.HEADER_SIZE + 11] ^= 0x01
    with pytest.raises(ChecksumError):
        checksum.decode_frame(bytes(flipped))          # CRC mismatch
    with pytest.raises(ChecksumError):
        checksum.decode_frame(checksum.encode_frame(b"abc")[:-1])  # short
    with pytest.raises(ChecksumError):
        checksum.decode_frame(b"NOPE" + checksum.encode_frame(b"abc")[4:])
    with pytest.raises(ChecksumError):
        checksum.decode_frame(b"\x00" * 5)             # truncated header
    with pytest.raises(ChecksumError):
        checksum.decode_frame_b64("!!! not base64 !!!")
    assert checksum.CodecStats.verify_failures == fails0 + 5


def test_frame_unknown_version_rejected():
    frame = checksum.encode_frame(b"abc")
    bad = frame[:4] + b"\x00\x63" + frame[6:]
    with pytest.raises(ChecksumError):
        checksum.decode_frame(bad)


def test_full_snapshot_codec_roundtrip():
    rt = make_rt(rooms=2)
    snap = rt.snapshot()
    blob = rt.encode_snapshot(snap)
    back = rt.decode_snapshot(blob)
    assert back["tick_index"] == snap["tick_index"]
    assert len(back["arrays"]) == len(snap["arrays"])
    assert len(back["munger"]) == len(snap["munger"])
    for a, b in zip(snap["arrays"], back["arrays"]):
        np.testing.assert_array_equal(np.asarray(a), b)
    # One flipped payload byte fails verification BEFORE np.load runs.
    tampered = bytearray(blob)
    tampered[checksum.HEADER_SIZE + 7] ^= 0xFF
    with pytest.raises(ChecksumError):
        rt.decode_snapshot(bytes(tampered))


def test_room_snapshot_codec_rejects_tamper():
    rt = make_rt(rooms=2)
    payload = rt.encode_room_snapshot(rt.snapshot_room(0))
    pos = 40
    repl = "A" if payload[pos] != "A" else "B"
    with pytest.raises(ChecksumError):
        rt.decode_room_snapshot(payload[:pos] + repl + payload[pos + 1:])


# -- audit kernel rules ------------------------------------------------------

def test_audit_clean_state():
    mask, counts = audit_once(make_rt())
    assert not mask.any()
    assert not counts.any()


def test_audit_rules_flag_expected_bits():
    rt = make_rt(rooms=5)
    poison(rt, "audio_state.smoothed_level", 0, jnp.nan)
    poison(rt, "temporal_bytes", 1, 1e35)          # finite but absurd
    poison(rt, "ctrl.max_spatial", 2, 7)
    poison(rt, "sel.current_spatial", 3, 99)
    poison(rt, "bwe_state.ring_pos", 4, -3)
    mask, counts = audit_once(rt)
    assert mask[0] & BIT_NONFINITE
    assert mask[1] & BIT_RANGE
    assert mask[2] & BIT_CTRL
    assert mask[3] & BIT_BOUNDS
    assert mask[4] & BIT_BOUNDS
    assert int(counts[AUDIT_RULES.index("nonfinite")]) == 1
    assert int(counts[AUDIT_RULES.index("bounds")]) == 2


def test_audit_cursor_regression_vs_legit_reset():
    rt = make_rt(rooms=2)
    s = rt.state.stats
    rt.state = rt.state._replace(stats=s._replace(
        started=s.started.at[0, 0].set(True),
        first_sn=s.first_sn.at[0, 0].set(17),
        highest_sn=s.highest_sn.at[0, 0].set(100),
    ))
    mirror = init_mirror(rt.state)
    regressed = mirror._replace(
        started=mirror.started.at[0, 0].set(True),
        first_sn=mirror.first_sn.at[0, 0].set(17),
        ext_sn=mirror.ext_sn.at[0, 0].set(200),    # cursor went backwards
    )
    mask, _, _ = audit_plane(rt.state, regressed)
    assert np.asarray(mask)[0] & BIT_CURSOR
    # Same regression but the stream identity changed (new first_sn):
    # that is a legitimate reset, not corruption.
    reset = regressed._replace(first_sn=regressed.first_sn.at[0, 0].set(18))
    mask, _, _ = audit_plane(rt.state, reset)
    assert not np.asarray(mask).any()


def test_audit_sn_wrap_is_monotonic():
    rt = make_rt(rooms=1)
    s = rt.state.stats
    # Post-wrap: highest_sn rewound 65530 -> 5 but sn_cycles advanced.
    rt.state = rt.state._replace(stats=s._replace(
        started=s.started.at[0, 0].set(True),
        first_sn=s.first_sn.at[0, 0].set(3),
        highest_sn=s.highest_sn.at[0, 0].set(5),
        sn_cycles=s.sn_cycles.at[0, 0].set(1),
    ))
    mirror = init_mirror(rt.state)._replace(
        started=rt.state.stats.started,
        first_sn=rt.state.stats.first_sn,
        ext_sn=jnp.zeros_like(rt.state.stats.highest_sn).at[0, 0].set(65530),
    )
    mask, _, _ = audit_plane(rt.state, mirror)
    assert not np.asarray(mask).any()


# -- end-to-end bitflip chaos ------------------------------------------------

async def _bitflip_scenario() -> dict:
    """Seeded silent-data-corruption drill: a bitflip lands in room 0's
    selector row at tick 5; the audit (cadence 4) must catch it at tick
    8, quarantine the room, and row-repair it from the checksummed
    checkpoint — while rooms 1 and 2 never drop an audio tick."""
    rt = make_rt(rooms=3)
    for i in range(2):
        push_audio(rt, range(3), i)
        await rt.step_once()
    async with rt.state_lock:
        snap = rt.snapshot()
    blob = rt.encode_snapshot(snap)   # checksummed at rest, like the sup ring
    mon = IntegrityMonitor(rt, audit_every_ticks=4, max_row_repairs=3,
                           storm_threshold=4)
    mon.snapshot_provider = lambda: rt.decode_snapshot(blob)
    escalations: list[str] = []
    mon.escalate_cb = escalations.append
    rt.integrity = mon
    # Target the BWE ring cursor: the tick only advances it on estimate
    # samples (none in an audio-only room), so the corruption persists
    # until the audit sees it, and ANY bit-30 flip lands out of bounds.
    rt.fault = FaultInjector(FaultSpec(
        seed=7, bitflip_tick=5, bitflip_room=0,
        bitflip_leaf="bwe_state.ring_pos", bitflip_bit=30, bitflip_count=2,
    ))
    witness_ok = True
    detection_tick = None
    repair_tick = None
    quarantined_seen = False
    for i in range(2, 14):
        push_audio(rt, range(3), i)
        res = await rt.step_once()
        if {p.room for p in res.egress} < {1, 2}:
            witness_ok = False                 # a witness room dropped a tick
        # Same-tick repair releases quarantine before step_once returns;
        # the monotonic counter proves the victim passed through it.
        quarantined_seen = quarantined_seen or mon.rows_quarantined > 0
        if detection_tick is None and mon.violations_total:
            detection_tick = mon.last_audit_tick
        if repair_tick is None and mon.rows_repaired:
            repair_tick = res.tick_index
    return {
        "bitflips": rt.fault.stats.bitflips,
        "detection_tick": detection_tick,
        "repair_tick": repair_tick,
        "quarantined_seen": quarantined_seen,
        "repaired": mon.rows_repaired,
        "escalations": len(escalations),
        "quarantined_now": sorted(mon.quarantined),
        "witness_ok": witness_ok,
        "ring_max": int(np.asarray(rt.state.bwe_state.ring_pos).max()),
        "rule_hits": dict(mon.rule_violations),
    }


async def test_bitflip_detected_quarantined_and_row_repaired():
    r = await _bitflip_scenario()
    assert r["bitflips"] == 2
    # Flip at tick 5, audit cadence 4: caught at tick 8 — within one window.
    assert r["detection_tick"] == 8
    assert r["quarantined_seen"]
    assert r["repaired"] == 1 and r["repair_tick"] == 8
    assert r["escalations"] == 0              # row repair, no full restart
    assert r["quarantined_now"] == []         # victim released after repair
    assert r["witness_ok"]                    # zero dropped witness ticks
    from livekit_server_tpu.ops import bwe
    assert r["ring_max"] < bwe.WINDOW         # corruption actually gone
    assert r["rule_hits"]["bounds"] >= 1


async def test_bitflip_chaos_is_deterministic():
    """Same seed → identical detection tick and repair path, twice."""
    assert await _bitflip_scenario() == await _bitflip_scenario()


# -- repair ladder escalation ------------------------------------------------

async def test_unrepairable_row_escalates_exactly_once():
    rt = make_rt(rooms=3)
    mon = IntegrityMonitor(rt, audit_every_ticks=1, max_row_repairs=2,
                           storm_threshold=4)
    reasons: list[str] = []
    mon.escalate_cb = reasons.append
    mon.snapshot_provider = lambda: None      # no verified checkpoint at all
    rt.integrity = mon
    poison(rt, "bwe_state.ring_pos", 1, 77)
    for i in range(4):
        push_audio(rt, range(3), i)
        await rt.step_once()
    assert mon.repair_failures >= 1
    assert len(reasons) == 1                  # epoch guard: one escalation
    assert 1 in mon.quarantined               # stays muted while suspect


async def test_repair_storm_escalates_to_supervisor_restart_once():
    rt = make_rt(rooms=6)
    for i in range(2):
        push_audio(rt, range(6), i)
        await rt.step_once()
    sup = PlaneSupervisor(
        rt, tick_deadline_s=5.0, check_interval_s=0.02,
        checkpoint_interval_s=60.0, max_restarts=5,
        backoff=BackoffPolicy(base=0.01, max_delay=0.05),
    )
    await sup.checkpoint_now()                # the (clean) restart seed
    mon = IntegrityMonitor(rt, audit_every_ticks=1, storm_threshold=2)
    mon.snapshot_provider = sup.last_good_snapshot
    mon.escalate_cb = sup.request_restart
    rt.integrity = mon
    for room in range(4):                     # 4 rooms > storm threshold 2
        poison(rt, "bwe_state.ring_pos", room, 77)
    rt.start()
    sup.start()
    try:
        await until(lambda: sup.restart_causes.get("integrity", 0) >= 1,
                    msg="integrity restart")
        base = rt.stats["ticks"]
        await until(lambda: rt.stats["ticks"] >= base + 5,
                    msg="post-restart ticks")
        assert sup.restart_causes["integrity"] == 1
        assert mon.escalations == 1
        assert not mon.quarantined            # on_full_restore cleared it
        from livekit_server_tpu.ops import bwe
        assert int(np.asarray(rt.state.bwe_state.ring_pos).max()) \
            < bwe.WINDOW                      # restored state is clean
        assert not sup.gave_up
    finally:
        await sup.stop()
        await rt.stop()


# -- checkpoint generations --------------------------------------------------

async def test_corrupt_checkpoint_falls_back_one_generation():
    rt = make_rt(rooms=2)
    push_audio(rt, range(2), 0)
    await rt.step_once()
    sup = PlaneSupervisor(rt, checkpoint_interval_s=60.0)
    await sup.checkpoint_now()                        # older, clean
    older_tick = sup.last_snapshot["tick_index"]
    for i in range(1, 3):
        push_audio(rt, range(2), i)
        await rt.step_once()
    await sup.checkpoint_now()                        # newest
    assert sup.last_snapshot["tick_index"] > older_tick
    flipped = bytearray(sup._gens[0])
    flipped[checksum.HEADER_SIZE + 5] ^= 0xFF         # rot the newest gen
    sup._gens[0] = bytes(flipped)
    snap = sup.last_good_snapshot()
    assert snap is not None
    assert snap["tick_index"] == older_tick           # fell back exactly one
    assert sup.ckpt_fallbacks == 1
    # Restore-from-checkpoint walks the same ladder.
    assert await sup._restore_from_checkpoint()
    assert rt.tick_index == older_tick
    assert sup.ckpt_fallbacks == 2


async def test_corrupt_ckpt_fault_seam():
    rt = make_rt(rooms=2)
    push_audio(rt, range(2), 0)
    await rt.step_once()
    sup = PlaneSupervisor(rt, checkpoint_interval_s=60.0)
    await sup.checkpoint_now()                        # clean (no fault yet)
    clean_tick = sup.last_snapshot["tick_index"]
    rt.fault = FaultInjector(FaultSpec(corrupt_ckpt_every=1))
    push_audio(rt, range(2), 1)
    await rt.step_once()
    await sup.checkpoint_now()                        # damaged at the seam
    assert rt.fault.stats.ckpt_corrupted == 1
    snap = sup.last_good_snapshot()
    assert snap is not None and snap["tick_index"] == clean_tick
    assert sup.ckpt_fallbacks == 1


async def test_generation_ring_keeps_k_checkpoints():
    rt = make_rt(rooms=2)
    sup = PlaneSupervisor(rt, checkpoint_interval_s=60.0, ckpt_generations=3)
    for _ in range(5):
        await sup.checkpoint_now()
    assert len(sup._gens) == 3


# -- restore-path hardening --------------------------------------------------

async def test_repair_rejects_mismatched_snapshot():
    rt = make_rt(rooms=2)
    push_audio(rt, range(2), 0)
    await rt.step_once()
    async with rt.state_lock:
        snap = rt.snapshot()
    row = rt.row_snapshot_from_full(snap, 0)
    async with rt.state_lock:
        with pytest.raises(ValueError, match="plane versions differ"):
            rt.repair_room_row(0, {"arrays": row["arrays"][:-1]})
        bad_shape = [np.zeros((9, 9, 9), np.float32)] + row["arrays"][1:]
        with pytest.raises(ValueError, match="row shape"):
            rt.repair_room_row(0, {"arrays": bad_shape})
        bad_dtype = list(row["arrays"])
        bad_dtype[0] = np.asarray(bad_dtype[0]).astype(np.complex64)
        with pytest.raises(ValueError, match="dtype"):
            rt.repair_room_row(0, {"arrays": bad_dtype})
    # A good row snapshot is still accepted after the rejections.
    async with rt.state_lock:
        rt.repair_room_row(0, row)


async def test_full_restore_rejects_wrong_plane():
    rt = make_rt(rooms=2)
    other = make_rt(rooms=3)                  # different [R] leading axis
    snap = other.snapshot()
    async with rt.state_lock:
        with pytest.raises(ValueError):
            rt.restore(snap)


# -- audit overhead ----------------------------------------------------------

@pytest.mark.slow
async def test_audit_overhead_under_5_percent():
    """At bench-ish dims on the default cadence, the audit's share of
    total tick wall time stays under 5%."""
    dims = plane.PlaneDims(rooms=64, tracks=8, pkts=8, subs=16)
    rt = PlaneRuntime(dims, tick_ms=10)
    for room in range(dims.rooms):
        rt.set_track(room, 0, published=True, is_video=False)
        rt.set_subscription(room, 0, 1, subscribed=True)
    mon = IntegrityMonitor(rt, audit_every_ticks=16)
    rt.integrity = mon
    for i in range(3):                        # compile tick
        push_audio(rt, range(dims.rooms), i)
        await rt.step_once()
    # Compile + warm the audit kernel off the clock too.
    mon.maybe_audit(0)
    mon.audit_s = 0.0
    t_base = rt.stats["stage_s"] + rt.stats["device_s"] + rt.stats["fanout_s"]
    for i in range(3, 67):
        push_audio(rt, range(dims.rooms), i)
        await rt.step_once()
    total = (rt.stats["stage_s"] + rt.stats["device_s"]
             + rt.stats["fanout_s"]) - t_base
    assert mon.audits >= 4
    assert mon.audit_s < 0.05 * total, \
        f"audit {mon.audit_s:.4f}s is >=5% of {total:.4f}s tick time"
