"""Wraparound arithmetic tests (reference: pkg/sfu/utils/wraparound_test.go)."""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import seqnum


def test_diff16_basic():
    assert int(seqnum.diff16(10, 5)) == 5
    assert int(seqnum.diff16(5, 10)) == -5
    assert int(seqnum.diff16(2, 65534)) == 4      # wrap forward
    assert int(seqnum.diff16(65534, 2)) == -4     # wrap backward
    assert int(seqnum.diff16(0, 32768)) == -32768


def test_diff32_wrap():
    a = jnp.int32(5)            # 5 as uint32
    b = jnp.int32(-5)           # 2^32-5 as uint32
    assert int(seqnum.diff32(a, b)) == 10
    assert int(seqnum.diff32(b, a)) == -10


def test_add_sub16():
    assert int(seqnum.add16(65535, 1)) == 0
    assert int(seqnum.sub16(0, 1)) == 65535
    assert int(seqnum.add16(100, 200)) == 300


def test_is_newer():
    assert bool(seqnum.is_newer16(1, 65535))
    assert not bool(seqnum.is_newer16(65535, 1))
    assert bool(seqnum.is_newer32(jnp.int32(-2147483648), jnp.int32(2147483647)))


def test_update_highest16_counts_cycles():
    highest = jnp.int32(65530)
    cycles = jnp.int32(0)
    for sn, want_h, want_c in [(65534, 65534, 0), (2, 2, 1), (1, 2, 1), (10, 10, 1)]:
        highest, cycles, _ = seqnum.update_highest16(highest, cycles, jnp.int32(sn))
        assert int(highest) == want_h
        assert int(cycles) == want_c


def test_update_highest16_vectorized():
    highest = jnp.array([100, 65535], jnp.int32)
    cycles = jnp.zeros(2, jnp.int32)
    new = jnp.array([99, 0], jnp.int32)
    h, c, newer = seqnum.update_highest16(highest, cycles, new)
    np.testing.assert_array_equal(np.asarray(h), [100, 0])
    np.testing.assert_array_equal(np.asarray(c), [0, 1])
    np.testing.assert_array_equal(np.asarray(newer), [False, True])
