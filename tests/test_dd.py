"""Dependency-descriptor parse/patch round trips.

Reference parity: pkg/sfu/dependencydescriptor/dependencydescriptor
extension_test.go — parse mandatory + extended + template structure,
active-decode-targets bitmask location and in-place rewrite.
"""

import pytest

from livekit_server_tpu.runtime import dd


def l2t2_structure():
    # 2 spatial x 2 temporal, 4 decode targets (dt = sid*2+tid), one
    # template per layer, simple fdiffs + chains.
    templates = [
        dd.Template(spatial=0, temporal=0, dtis=[3, 2, 3, 2], fdiffs=[4],
                    chain_diffs=[4, 0]),
        dd.Template(spatial=0, temporal=1, dtis=[0, 3, 0, 2], fdiffs=[2],
                    chain_diffs=[2, 2]),
        dd.Template(spatial=1, temporal=0, dtis=[0, 0, 3, 2], fdiffs=[1, 4],
                    chain_diffs=[1, 1]),
        dd.Template(spatial=1, temporal=1, dtis=[0, 0, 0, 3], fdiffs=[2, 1],
                    chain_diffs=[2, 1]),
    ]
    return dd.Structure(
        structure_id=3, num_decode_targets=4, templates=templates,
        num_chains=2, protected_by=[0, 0, 1, 1],
        resolutions=[(640, 360), (1280, 720)],
    )


def test_mandatory_only_roundtrip():
    raw = dd.build(True, False, template_id=5, frame_number=0xBEEF)
    assert len(raw) == 3
    d = dd.parse(raw)
    assert d.first_packet_in_frame and not d.last_packet_in_frame
    assert d.template_id == 5 and d.frame_number == 0xBEEF
    assert d.structure is None and d.active_mask is None


def test_structure_roundtrip_and_layers():
    s = l2t2_structure()
    raw = dd.build(True, True, template_id=3, frame_number=7, structure=s)
    d = dd.parse(raw)
    assert d.structure is not None
    got = d.structure
    assert got.structure_id == 3 and got.num_decode_targets == 4
    assert [(t.spatial, t.temporal) for t in got.templates] == [
        (0, 0), (0, 1), (1, 0), (1, 1)
    ]
    assert [t.dtis for t in got.templates] == [t.dtis for t in s.templates]
    assert [t.fdiffs for t in got.templates] == [t.fdiffs for t in s.templates]
    assert got.num_chains == 2 and got.protected_by == [0, 0, 1, 1]
    assert got.resolutions == [(640, 360), (1280, 720)]
    # Structure attach => all decode targets active.
    assert d.active_mask == 0b1111
    # dt -> max (spatial, temporal) map for the selector.
    assert got.decode_target_layers() == [(0, 0), (0, 1), (1, 0), (1, 1)]
    # Packet layer via template id (relative to structure_id).
    assert d.layer(got) == (0, 0)
    d2 = dd.parse(dd.build(True, True, template_id=4, frame_number=8))
    assert d2.layer(got) == (0, 1)   # relative index 4-3 = 1
    d3 = dd.parse(dd.build(True, True, template_id=5, frame_number=9))
    assert d3.layer(got) == (1, 0)   # relative index 2


def test_active_mask_needs_structure_and_patch():
    s = l2t2_structure()
    raw = dd.build(False, True, template_id=4, frame_number=9,
                   active_mask=0b1111, mask_bits=4)
    with pytest.raises(dd.NeedStructure):
        dd.parse(raw)
    d = dd.parse_with_structure(raw, s)
    assert d.active_mask == 0b1111 and d.active_mask_bit_off > 0

    # In-place restriction to spatial 0 only (targets 0,1).
    buf = bytearray(raw)
    assert dd.patch_active_mask(buf, 0, d, 0b0011)
    d3 = dd.parse_with_structure(bytes(buf), s)
    assert d3.active_mask == 0b0011
    # Everything else untouched.
    assert d3.template_id == 4 and d3.frame_number == 9


def test_mask_patch_with_structure_packet():
    s = l2t2_structure()
    raw = dd.build(True, True, template_id=3, frame_number=1, structure=s,
                   active_mask=0b1111, mask_bits=4)
    d = dd.parse(raw)
    assert d.active_mask == 0b1111 and d.active_mask_bit_off > 0
    buf = bytearray(raw)
    assert dd.patch_active_mask(buf, 0, d, 0b0101)
    assert dd.parse(bytes(buf)).active_mask == 0b0101


def test_truncated_dd_rejected():
    s = l2t2_structure()
    raw = dd.build(True, True, template_id=3, frame_number=7, structure=s)
    with pytest.raises(ValueError):
        dd.parse(raw[:5])


def test_custom_frame_deps_roundtrip():
    """frame_dependency_definition: custom dtis/fdiffs/chain-fdiffs decode
    (dependencydescriptorreader.go readFrameDtis/Fdiffs/Chains)."""
    s = l2t2_structure()
    raw = dd.build(
        True, True, template_id=3, frame_number=10, structure=s,
        active_mask=0b1011,
        custom_dtis=[3, 0, 2, 1],
        custom_fdiffs=[2, 17, 300],     # 1-, 2-, 3-nibble widths
        custom_chain_fdiffs=[7, 200],
    )
    d = dd.parse(raw)
    assert d.custom_dtis == [3, 0, 2, 1]
    assert d.custom_fdiffs == [2, 17, 300]
    assert d.custom_chain_fdiffs == [7, 200]
    assert d.active_mask == 0b1011
    # Custom dtis take precedence over the template's.
    assert d.effective_dtis(d.structure) == [3, 0, 2, 1]
    d_plain = dd.parse(dd.build(True, True, template_id=3, frame_number=11,
                                structure=s))
    assert d_plain.effective_dtis(d_plain.structure) == [3, 2, 3, 2]

    # Without an attached structure the widths need the cache.
    raw2 = dd.build(False, True, template_id=4, frame_number=12,
                    custom_dtis=[0, 3, 0, 2], custom_chain_fdiffs=[1, 2],
                    mask_bits=0)
    with pytest.raises(dd.NeedStructure):
        dd.parse(raw2)
    d2 = dd.parse_with_structure(raw2, s)
    assert d2.custom_dtis == [0, 3, 0, 2]
    assert d2.custom_chain_fdiffs == [1, 2]
    # custom fdiffs alone need no structure at all
    raw3 = dd.build(False, False, template_id=4, frame_number=13,
                    custom_fdiffs=[1])
    assert dd.parse(raw3).custom_fdiffs == [1]


def test_refine_layer_honors_custom_dtis():
    """A frame marked not-present for low decode targets gets its
    effective temporal raised; absent everywhere at its spatial → dropped
    for every subscriber (the custom-dti precedence the reference's DD
    selector applies)."""
    s = l2t2_structure()
    # Template (0,0) normally feeds dts 0..3. Custom dtis mark the frame
    # present ONLY for dt1 (s0,t1) and dt3 (s1,t1) → effective temporal 1.
    raw = dd.build(True, True, template_id=3, frame_number=20, structure=s,
                   custom_dtis=[0, 1, 0, 1])
    d = dd.parse(raw)
    assert d.layer(d.structure) == (0, 0)
    assert d.refine_layer(d.structure) == (0, 1)
    # No custom dtis → template behavior, unchanged.
    d2 = dd.parse(dd.build(True, True, template_id=3, frame_number=21,
                           structure=s))
    assert d2.refine_layer(d2.structure) == d2.layer(d2.structure)
    # Absent from every decode target at its spatial layer → MAX_TEMPORAL
    # (forwarded to nobody).
    raw3 = dd.build(True, True, template_id=3, frame_number=22, structure=s,
                    custom_dtis=[0, 0, 0, 0])
    d3 = dd.parse(raw3)
    assert d3.refine_layer(d3.structure) == (0, dd.MAX_TEMPORAL)
