"""Native RTP parser tests: C++ batch parser vs pure-Python reference.

Reference parity: the parsing behaviors of pkg/sfu/buffer/buffer.go:417
(header, RFC 8285 extensions, RFC 6464 audio level) and buffer/vp8.go
(VP8 payload descriptor). Packets are hand-crafted here, parsed by both
implementations, and must agree field-for-field.
"""

import numpy as np
import pytest

from livekit_server_tpu.native import PARSED_DTYPE, _PythonRTP, rtp


def rtp_packet(
    sn=100, ts=9000, ssrc=0x1234, pt=111, marker=0, audio_level=None,
    payload=b"\xaa" * 20, csrcs=0, padding=0,
):
    b = bytearray()
    b0 = 0x80 | (csrcs & 0x0F)
    if audio_level is not None:
        b0 |= 0x10
    if padding:
        b0 |= 0x20
    b.append(b0)
    b.append((marker << 7) | pt)
    b += sn.to_bytes(2, "big") + ts.to_bytes(4, "big") + ssrc.to_bytes(4, "big")
    b += b"\x00" * (4 * csrcs)
    if audio_level is not None:
        # one-byte ext: id=1, len=1, V|level
        ext = bytes([0x10 | 0x00, 0x80 | audio_level, 0, 0])
        b += (0xBEDE).to_bytes(2, "big") + (1).to_bytes(2, "big") + ext
    b += payload
    if padding:
        b += b"\x00" * (padding - 1) + bytes([padding])
    return bytes(b)


def vp8_payload(pid=None, tl0=None, tid=None, ysync=0, keyidx=None, sbit=1, keyframe=True):
    d = bytearray()
    x = pid is not None or tl0 is not None or tid is not None or keyidx is not None
    b0 = (0x80 if x else 0) | (0x10 if sbit else 0)
    d.append(b0)
    if x:
        xb = 0
        if pid is not None:
            xb |= 0x80
        if tl0 is not None:
            xb |= 0x40
        if tid is not None:
            xb |= 0x20
        if keyidx is not None:
            xb |= 0x10
        d.append(xb)
        if pid is not None:
            if pid > 127:
                d += bytes([0x80 | (pid >> 8), pid & 0xFF])
            else:
                d.append(pid)
        if tl0 is not None:
            d.append(tl0)
        if tid is not None or keyidx is not None:
            d.append(((tid or 0) << 6) | (ysync << 5) | ((keyidx or 0) & 0x1F))
    d.append(0x00 if keyframe else 0x01)  # first VP8 byte: P bit
    d += b"\xbb" * 10
    return bytes(d)


def parse_both(datagrams, **kw):
    buf = b"".join(datagrams)
    offsets, lengths, off = [], [], 0
    for d in datagrams:
        offsets.append(off)
        lengths.append(len(d))
        off += len(d)
    offs = np.asarray(offsets, np.int32)
    lens = np.asarray(lengths, np.int32)
    a = rtp.parse_batch(buf, offs, lens, **kw)
    b = _PythonRTP().parse_batch(buf, offs, lens, **kw)
    return a, b


def test_native_library_built():
    # The image ships g++; the native path must actually be in use.
    assert rtp.native, "native librtp_parser.so failed to build"
    assert PARSED_DTYPE.itemsize == 52  # C struct layout match


def test_parse_basic_and_audio_level():
    pkts = [
        rtp_packet(sn=1, ts=1000, ssrc=7, audio_level=23),
        rtp_packet(sn=2, ts=2000, ssrc=7),
        rtp_packet(sn=3, ts=3000, ssrc=8, padding=4, payload=b"\xcc" * 8),
    ]
    a, b = parse_both(pkts, audio_level_ext=1)
    for out in (a, b):
        assert out["sn"].tolist() == [1, 2, 3]
        assert out["ssrc"].tolist() == [7, 7, 8]
        assert out["audio_level"].tolist() == [23, 127, 127]
        assert out["voice"].tolist() == [1, 0, 0]
        assert out["payload_len"].tolist() == [20, 20, 8]
    assert bytes(a.tobytes()) == bytes(b.tobytes())  # exact agreement


def test_parse_vp8_descriptor():
    pkts = [
        rtp_packet(pt=96, payload=vp8_payload(pid=300, tl0=9, tid=1, ysync=1, keyidx=3, keyframe=True)),
        rtp_packet(pt=96, payload=vp8_payload(pid=55, keyframe=False)),
        rtp_packet(pt=96, payload=vp8_payload(sbit=0, pid=None, keyframe=False)),
    ]
    a, b = parse_both(pkts, audio_level_ext=1, vp8_pts={96})
    for out in (a, b):
        assert out["is_vp8"].tolist() == [1, 1, 1]
        assert out["picture_id"].tolist() == [300, 55, -1]
        assert out["tl0picidx"].tolist() == [9, -1, -1]
        assert out["tid"].tolist() == [1, 0, 0]
        assert out["layer_sync"].tolist() == [1, 0, 0]
        assert out["keyframe"].tolist() == [1, 0, 0]
        assert out["begin_pic"].tolist() == [1, 1, 0]
    assert bytes(a.tobytes()) == bytes(b.tobytes())


def test_parse_garbage_rejected():
    pkts = [b"\x00" * 5, b"not rtp at all!!", rtp_packet(sn=9)]
    a, b = parse_both(pkts)
    for out in (a, b):
        assert out["payload_len"].tolist()[:2] == [-1, -1]
        assert out["sn"][2] == 9
    assert bytes(a.tobytes()) == bytes(b.tobytes())


def test_rewrite_batch():
    pkt = bytearray(rtp_packet(sn=1, ts=2, ssrc=3))
    rtp.rewrite_batch(
        pkt, np.asarray([0], np.int32), np.asarray([777], np.uint16),
        np.asarray([123456], np.uint32), np.asarray([0xDEAD], np.uint32),
    )
    out = rtp.parse_batch(bytes(pkt), np.asarray([0], np.int32), np.asarray([len(pkt)], np.int32))
    assert int(out["sn"][0]) == 777
    assert int(out["ts"][0]) == 123456
    assert int(out["ssrc"][0]) == 0xDEAD


def test_rewrite_vp8_batch_patches_descriptor():
    """The egress rewrite must patch picture-id/TL0PICIDX/KEYIDX inside the
    VP8 payload descriptor (codecmunger/vp8.go:161), preserving TID/Y bits
    and the VP8 bitstream bytes after the descriptor."""
    pay15 = vp8_payload(pid=3000, tl0=7, tid=1, ysync=1, keyidx=4)
    pay7 = vp8_payload(pid=90, tl0=8, tid=0, keyidx=5)
    pkts = [
        bytearray(rtp_packet(sn=1, ts=10, ssrc=1, pt=96, payload=pay15)),
        bytearray(rtp_packet(sn=2, ts=20, ssrc=1, pt=96, payload=pay7)),
        bytearray(rtp_packet(sn=3, ts=30, ssrc=2, pt=111)),  # audio untouched
    ]
    buf = bytearray(b"".join(pkts))
    offsets = np.asarray([0, len(pkts[0]), len(pkts[0]) + len(pkts[1])], np.int32)
    lengths = np.asarray([len(p) for p in pkts], np.int32)
    rtp.rewrite_vp8_batch(
        buf, offsets, lengths,
        np.asarray([11, 12, 13], np.uint16),
        np.asarray([110, 120, 130], np.uint32),
        np.asarray([9, 9, 9], np.uint32),
        np.asarray([4500, 21, -1], np.int32),   # new picture ids
        np.asarray([70, 80, -1], np.int32),     # new tl0
        np.asarray([1, 2, -1], np.int32),       # new keyidx
        np.asarray([1, 1, 0], np.uint8),
    )
    out = rtp.parse_batch(
        bytes(buf), offsets, lengths, audio_level_ext=1, vp8_pts={96}
    )
    # 15-bit pid slot carries the new pid; tl0/keyidx patched; tid/Y kept.
    assert int(out["sn"][0]) == 11 and int(out["ssrc"][0]) == 9
    assert int(out["picture_id"][0]) == 4500
    assert int(out["tl0picidx"][0]) == 70
    assert int(out["keyidx"][0]) == 1
    assert int(out["tid"][0]) == 1 and int(out["layer_sync"][0]) == 1
    # 7-bit slot: low 7 bits, width preserved.
    assert int(out["picture_id"][1]) == 21
    assert int(out["tl0picidx"][1]) == 80
    assert int(out["keyidx"][1]) == 2
    # VP8 bitstream bytes after the descriptor untouched (keyframe P bit).
    assert int(out["keyframe"][0]) == 1
    # Audio packet: header rewritten, payload untouched.
    assert int(out["sn"][2]) == 13
    off, ln = int(out["payload_off"][2]), int(out["payload_len"][2])
    base = int(offsets[2])
    assert bytes(buf[base + off : base + off + ln]) == b"\xaa" * 20


def test_rewrite_vp8_batch_python_native_agree():
    """Native and fallback rewriters must produce identical bytes."""
    from livekit_server_tpu.native import _PythonRTP

    rng = np.random.default_rng(7)
    pkts = []
    for i in range(40):
        pay = vp8_payload(
            pid=int(rng.integers(0, 0x7FFF)) if rng.random() < 0.8 else None,
            tl0=int(rng.integers(0, 255)) if rng.random() < 0.7 else None,
            tid=int(rng.integers(0, 3)) if rng.random() < 0.7 else None,
            keyidx=int(rng.integers(0, 31)) if rng.random() < 0.5 else None,
            keyframe=bool(rng.random() < 0.3),
        )
        pkts.append(rtp_packet(sn=i, ts=i * 90, ssrc=5, pt=96, payload=pay))
    offsets, lengths, off = [], [], 0
    for p in pkts:
        offsets.append(off)
        lengths.append(len(p))
        off += len(p)
    offsets = np.asarray(offsets, np.int32)
    lengths = np.asarray(lengths, np.int32)
    args = (
        np.arange(40, dtype=np.uint16),
        np.arange(40, dtype=np.uint32) * 10,
        np.full(40, 77, np.uint32),
        rng.integers(-1, 0x7FFF, 40).astype(np.int32),
        rng.integers(-1, 255, 40).astype(np.int32),
        rng.integers(-1, 31, 40).astype(np.int32),
        np.ones(40, np.uint8),
    )
    buf_a = bytearray(b"".join(pkts))
    buf_b = bytearray(b"".join(pkts))
    rtp.rewrite_vp8_batch(buf_a, offsets, lengths, *args)
    _PythonRTP().rewrite_vp8_batch(buf_b, offsets, lengths, *args)
    assert bytes(buf_a) == bytes(buf_b)


def test_fuzz_agreement():
    """Random bytes: native and Python must classify identically (no
    crashes, no disagreement on validity)."""
    rng = np.random.default_rng(0)
    pkts = [bytes(rng.integers(0, 256, rng.integers(0, 60), dtype=np.uint8).tobytes()) for _ in range(100)]
    a, b = parse_both(pkts, audio_level_ext=1, vp8_pts={96})
    assert bytes(a.tobytes()) == bytes(b.tobytes())
