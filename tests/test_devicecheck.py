"""devicecheck + GC10/GC11/GC12 unit tests.

Fixture projects exercise each of the three device-plane AST rules on
minimal good/bad modules; the contract half (`diff_contracts`,
`audit_donation`, the CompileLedger watchdog) is tested directly on
fake avals and the committed baseline — never through a full
`compute_contracts()` trace, which belongs to `tools/check` and would
blow this module's CPU budget.
"""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from livekit_server_tpu.analysis import (
    core,
    devicecheck,
    gc10,
    gc11,
    gc12,
    load_project,
    run_all,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


def make_project(tmp_path, files: dict[str, str]):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return load_project(tmp_path, ["pkg"])


def cfg_for(rule: str, **overrides) -> dict:
    merged = dict(core.DEFAULT_CONFIG[rule])
    merged["paths"] = ["pkg"]
    merged.update(overrides)
    return merged


def lines_of(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


# -- GC10: donation discipline ----------------------------------------------

GC10_MISSING = """\
    import jax

    def tick(state, pkt):
        return state + pkt

    step = jax.jit(tick)  # line 6: mutated state, no donation
"""


def test_gc10_missing_donation(tmp_path):
    project = make_project(tmp_path, {"pkg/rt.py": GC10_MISSING})
    findings = gc10.run(project, cfg_for("gc10"))
    assert lines_of(findings, "GC10") == [6]
    assert "missing donation" in findings[0].message


def test_gc10_donated_is_clean(tmp_path):
    src = GC10_MISSING.replace(
        "jax.jit(tick)  # line 6: mutated state, no donation",
        "jax.jit(tick, donate_argnums=(0,))",
    )
    project = make_project(tmp_path, {"pkg/rt.py": src})
    assert gc10.run(project, cfg_for("gc10")) == []


def test_gc10_init_paths_allowlisted(tmp_path):
    src = """\
        import jax

        def init_state(state):
            return state * 0

        build = jax.jit(init_state)
    """
    project = make_project(tmp_path, {"pkg/rt.py": src})
    assert gc10.run(project, cfg_for("gc10")) == []


def test_gc10_dead_donation_out_of_range_and_unused(tmp_path):
    src = """\
        import jax

        def tick(state, aux):
            return state * 2

        a = jax.jit(tick, donate_argnums=(5,))   # line 6: out of range
        b = jax.jit(tick, donate_argnums=(1,))   # line 7: aux never used
    """
    project = make_project(tmp_path, {"pkg/rt.py": src})
    findings = gc10.run(project, cfg_for("gc10"))
    assert lines_of(findings, "GC10") == [6, 7]
    assert any("out of range" in f.message for f in findings)
    assert any("never uses" in f.message for f in findings)


def test_gc10_semantic_audit_on_avals():
    import jax
    import jax.numpy as jnp

    big = jax.ShapeDtypeStruct((512, 1024), jnp.float32)   # 2 MiB
    small = jax.ShapeDtypeStruct((8,), jnp.int32)
    out = {"state": big, "count": small}

    # donated state aliases the matching output leaf: clean
    assert devicecheck.audit_donation((big, small), out, (0,)) == []

    # donate index past the argument list
    probs = devicecheck.audit_donation((big,), out, (3,))
    assert any("out of range" in p for p in probs)

    # donated leaf with no shape/dtype match in the outputs
    lone = jax.ShapeDtypeStruct((7, 7), jnp.float64)
    probs = devicecheck.audit_donation((big, lone), out, (1,))
    assert any(p.startswith("dead:") for p in probs)

    # >=1 MiB input matching an output but not donated
    probs = devicecheck.audit_donation((big,), out, ())
    assert any(p.startswith("missing:") for p in probs)
    assert devicecheck.audit_donation(
        (big,), out, (), allow_no_donate=True) == []


# -- GC11: retrace stability ------------------------------------------------

def test_gc11_unknown_static_name_and_mutable_default(tmp_path):
    src = """\
        import jax

        def mix(x, top_k):
            return x * top_k

        def pool(x, knobs={}):
            return x

        a = jax.jit(mix, static_argnames=("topk",))    # typo
        b = jax.jit(pool, static_argnames=("knobs",))  # default is a dict
    """
    project = make_project(tmp_path, {"pkg/ops.py": src})
    findings = gc11.run(project, cfg_for("gc11"))
    assert any("not a parameter" in f.message for f in findings)
    assert any("mutable default" in f.message for f in findings)


def test_gc11_mutable_literal_for_static_param(tmp_path):
    src = """\
        import jax

        def mix(x, ks):
            return x

        jmix = jax.jit(mix, static_argnames=("ks",))

        def caller(x):
            return mix(x, ks=[1, 2])    # line 9: unhashable static
    """
    project = make_project(tmp_path, {"pkg/ops.py": src})
    findings = gc11.run(project, cfg_for("gc11"))
    assert lines_of(findings, "GC11") == [9]
    assert "mutable literal" in findings[0].message


def test_gc11_per_call_jit(tmp_path):
    src = """\
        import functools
        import jax

        def hot(x):
            return jax.jit(lambda y: y * 2)(x)    # line 5: fresh wrapper

        @functools.lru_cache(maxsize=None)
        def builder(n):
            return jax.jit(lambda y: y * n)       # memoized: fine
    """
    project = make_project(tmp_path, {"pkg/ops.py": src})
    findings = gc11.run(project, cfg_for("gc11"))
    assert lines_of(findings, "GC11") == [5]
    assert "fresh" in findings[0].message


# -- GC12: host-sync hygiene ------------------------------------------------

GC12_SRC = """\
    import jax
    import numpy as np

    class Rt:
        def _device_step(self, state):
            out = self._fwd(state)
            jax.block_until_ready(out)       # line 7: mid-tick stall
            n = int(out.sum())               # line 8: blocking scalar read
            self._drain(out)
            self._helper(out)
            return out

        def _fwd(self, state):
            return state

        def _drain(self, out):
            return np.asarray(out)           # declared seam: fine

        def _helper(self, out):
            return out.item()                # line 20: reachable read
"""


def test_gc12_flags_reads_outside_seams(tmp_path):
    project = make_project(tmp_path, {"pkg/rt.py": GC12_SRC})
    cfg = cfg_for(
        "gc12",
        roots=["Rt._device_step"],
        seams=["*._drain"],
    )
    findings = gc12.run(project, cfg)
    assert lines_of(findings, "GC12") == [7, 8, 20]
    # the seam's own np.asarray is sanctioned
    assert all(f.line != 17 for f in findings)


def test_gc12_host_data_casts_are_clean(tmp_path):
    src = """\
        class Rt:
            def _device_step(self, state):
                host = self._counts()
                return int(host.sum())       # host numpy: no device name

            def _counts(self):
                return None
    """
    project = make_project(tmp_path, {"pkg/rt.py": src})
    cfg = cfg_for("gc12", roots=["Rt._device_step"], seams=[])
    assert gc12.run(project, cfg) == []


# -- stale suppressions -----------------------------------------------------

def run_all_pkg(project, stale=None, rules=None):
    config = core.Config(root=project.root, paths=["pkg"])
    config.rules = {r.lower(): {"paths": ["pkg"]} for r in core.RULES}
    return run_all(project, config, rules=rules, stale_suppressions=stale)


def test_live_suppression_is_not_stale(tmp_path):
    src = GC10_MISSING.replace(
        "# line 6: mutated state, no donation",
        "# graftcheck: disable=GC10",
    )
    project = make_project(tmp_path, {"pkg/rt.py": src})
    stale: list = []
    assert run_all_pkg(project, stale, rules=["GC10"]) == []
    assert stale == []


def test_stale_suppression_is_flagged(tmp_path):
    src = """\
        def fine():
            return 1  # graftcheck: disable=GC10
    """
    project = make_project(tmp_path, {"pkg/rt.py": src})
    stale: list = []
    assert run_all_pkg(project, stale, rules=["GC10"]) == []
    assert [f.rule for f in stale] == [core.PARSE_RULE]
    assert "stale suppression" in stale[0].message


# -- compile contracts: baseline drift --------------------------------------

def _committed_baseline() -> dict:
    cfg = core.load_config(REPO_ROOT).rule("devicecheck")
    return devicecheck.load_baseline(REPO_ROOT / cfg["baseline"])


def test_committed_baseline_matches_registry():
    base = _committed_baseline()
    assert "plane.media_plane_tick" in base
    assert "mesh.sharded_tick" in base
    tick = base["plane.media_plane_tick"]
    assert tick["donate"] == [0] and tick["flops"] > 0
    # the mesh entry carries explicit output sharding specs
    assert any("rooms" in s for s in base["mesh.sharded_tick"]["sharding"])


def test_diff_contracts_clean_on_identity():
    base = _committed_baseline()
    findings, stale = devicecheck.diff_contracts(base, base)
    assert findings == [] and stale == []


def test_diff_contracts_detects_drift():
    base = _committed_baseline()
    name = "plane.media_plane_tick"
    got = {name: json.loads(json.dumps(base[name]))}

    # shape drift on an output leaf
    got[name]["out"][0]["shape"] = [1, 2, 3]
    findings, _ = devicecheck.diff_contracts(got, base)
    assert any("output contract drifted" in f.message for f in findings)

    # cost drift beyond the tolerance band
    got = {name: json.loads(json.dumps(base[name]))}
    got[name]["flops"] = base[name]["flops"] * 3
    findings, _ = devicecheck.diff_contracts(got, base)
    assert any("flops drifted" in f.message for f in findings)

    # cost drift inside the band is tolerated
    got[name]["flops"] = int(base[name]["flops"] * 1.1)
    findings, _ = devicecheck.diff_contracts(
        got, base, cost_rtol=0.25)
    assert findings == []

    # donation drift
    got = {name: json.loads(json.dumps(base[name]))}
    got[name]["donate"] = []
    findings, _ = devicecheck.diff_contracts(got, base)
    assert any("donation contract drifted" in f.message for f in findings)


def test_diff_contracts_new_and_stale_entries():
    base = _committed_baseline()
    name = "plane.media_plane_tick"
    # an uncommitted entry must fail until snapshotted...
    got = dict(base)
    got["plane.brand_new"] = dict(base[name])
    findings, stale = devicecheck.diff_contracts(got, base)
    assert any("no committed contract" in f.message for f in findings)
    # ...and a deleted entry leaves its contract stale (shrink-only)
    got = {k: v for k, v in base.items() if k != name}
    findings, stale = devicecheck.diff_contracts(got, base)
    assert stale == [name]
    # drift findings carry a real file:line anchor
    sited, _ = devicecheck.diff_contracts(
        {name: {**base[name], "donate": []}}, base)
    assert sited[0].path.endswith("models/plane.py") and sited[0].line > 0


# -- recompile watchdog: CompileLedger --------------------------------------

def test_compile_ledger_counts_post_warmup_retraces():
    import jax
    import jax.numpy as jnp

    from livekit_server_tpu.runtime.compile_ledger import LEDGER

    LEDGER.install()
    LEDGER.reset()
    step = jax.jit(lambda x: x * 2.0 + 1.0)
    step(jnp.zeros((8,), jnp.float32)).block_until_ready()
    assert LEDGER.total >= 1, "warmup compile not observed"
    LEDGER.mark_warm()

    # same shape → executable cache hit, no compile event
    step(jnp.ones((8,), jnp.float32)).block_until_ready()
    assert LEDGER.post_warmup == 0

    # new shape → retrace + fresh XLA compile, the watchdog trips
    step(jnp.zeros((9,), jnp.float32)).block_until_ready()
    assert LEDGER.post_warmup >= 1
    snap = LEDGER.snapshot()
    assert snap["xla_compiles_post_warmup"] == LEDGER.post_warmup
    assert snap["xla_compiles_total"] >= 2
    assert snap["xla_warmup_compile_ms"] >= 0.0
    LEDGER.reset()
    LEDGER.install()


# -- the real tree ----------------------------------------------------------

def test_real_tree_device_rules_clean():
    """GC10–GC12 + the stale-suppression pass over the live repo: zero
    findings, zero dead directives."""
    config = core.load_config(REPO_ROOT)
    project = load_project(REPO_ROOT, config.paths)
    stale: list = []
    findings = run_all(
        project, config, rules=["GC10", "GC11", "GC12"],
        stale_suppressions=stale,
    )
    assert findings == [], "\n".join(f.render() for f in findings)
    assert stale == [], "\n".join(f.render() for f in stale)
