"""Three-stage tick pipeline (runtime/plane_runtime.py _run).

Covers the PR's pipeline invariants end to end: the step_once/serving-loop
mutual exclusion guard, cross-tick egress ordering under overlap, bounded
pipeline depth when the device stalls (faultinject), dirty-row delta
control uploads vs the full `_replace` path, and the double-buffered
ingest staging sets that let stage N+1 overlap device N.
"""

import asyncio

import numpy as np
import pytest

from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.faultinject import FaultInjector, FaultSpec
from livekit_server_tpu.runtime.ingest import IngestBuffer, PacketIn

DIMS = plane.PlaneDims(rooms=2, tracks=2, pkts=4, subs=4)


async def _first_tick(rt, timeout=60.0):
    """Wait out the first tick (it pays the jit compile)."""
    deadline = asyncio.get_event_loop().time() + timeout
    while rt.stats["ticks"] < 1:
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError("first tick never completed")
        await asyncio.sleep(0.02)


# -- step_once vs the serving loop ------------------------------------------

async def test_step_once_raises_while_loop_running():
    """step_once interleaved with the pipelined loop would fan out ahead
    of the loop's deferred fan-out of an earlier tick (munger lanes
    rewritten backwards) — it must refuse, hard, while the loop runs,
    and work again once the loop has stopped."""
    rt = PlaneRuntime(DIMS, tick_ms=10)
    rt.start()
    try:
        await _first_tick(rt)
        with pytest.raises(RuntimeError, match="serving loop"):
            await rt.step_once()
    finally:
        await rt.stop()
    res = await rt.step_once()  # sequential stepping is fine again
    assert res.tick_index >= 1


# -- ordering under overlap --------------------------------------------------

async def test_pipelined_egress_stays_in_tick_order():
    """With fan-out N-1 overlapping device N, completions must still be
    delivered strictly in tick order and every SN exactly once: the
    pipeline reorders WORK, never egress."""
    rt = PlaneRuntime(DIMS, tick_ms=10)  # pipelined (low_latency=False)
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    ticks, batches = [], []
    rt.on_tick(lambda res: (ticks.append(res.tick_index),
                            batches.append(res.egress_batch)))
    rt.start()
    try:
        await _first_tick(rt)
        for i in range(8):
            rt.ingest.push(PacketIn(room=0, track=0, sn=700 + i, ts=960 * i,
                                    size=40, payload=b"p" * 40))
            await asyncio.sleep(0.015)
        deadline = asyncio.get_event_loop().time() + 5.0
        while sum(len(b) for b in batches) < 8:
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"only {sum(len(b) for b in batches)} sends arrived"
                )
            await asyncio.sleep(0.01)
    finally:
        await rt.stop()
    assert ticks == sorted(ticks) and len(set(ticks)) == len(ticks)
    sns = [int(sn) & 0xFFFF for b in batches for sn in np.asarray(b.sn)]
    # In arrival order across callbacks: monotonic, no dupes, no holes.
    assert sns == [700 + i for i in range(len(sns))]
    assert len(sns) >= 8
    # Munger lane advanced once per delivered packet (a double fan-out
    # would overshoot).
    assert int(rt.munger.last_sn[0, 0, 1]) == sns[-1]


async def test_device_stall_degrades_to_sequential_bounded_depth():
    """A stalling device (faultinject stall_every) must hold the pipeline
    at depth ≤ 1 — the loop degrades to sequential (pipeline_stalls
    counts the backpressure) rather than queueing stale sends. Every
    delivered SN still appears exactly once, in order."""
    rt = PlaneRuntime(DIMS, tick_ms=10)
    rt.fault = FaultInjector(FaultSpec(stall_every=2, stall_s=0.05))
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    batches = []
    rt.on_tick(lambda res: batches.append(res.egress_batch))
    rt.start()
    try:
        await _first_tick(rt)
        for i in range(6):
            rt.ingest.push(PacketIn(room=0, track=0, sn=900 + i, ts=960 * i,
                                    size=40, payload=b"q" * 40))
            await asyncio.sleep(0.03)
            # Staged-but-not-dispatched never runs ahead: at most one tick
            # is in flight on the device plus one staged behind it.
            assert rt.tick_index - rt.stats["ticks"] <= 2
        deadline = asyncio.get_event_loop().time() + 5.0
        while sum(len(b) for b in batches) < 6:
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"only {sum(len(b) for b in batches)} sends arrived"
                )
            await asyncio.sleep(0.01)
    finally:
        await rt.stop()
    assert rt.fault.stats.stalls >= 2
    sns = [int(sn) & 0xFFFF for b in batches for sn in np.asarray(b.sn)]
    assert sns == [900 + i for i in range(len(sns))]
    assert all(rec["depth"] <= 1 for rec in rt.recent_ticks)


# -- dirty-row delta control uploads ----------------------------------------

def _churn(rt, rng):
    """One round of subscription/meta churn across a few rooms."""
    for _ in range(4):
        r = int(rng.integers(rt.dims.rooms))
        t = int(rng.integers(rt.dims.tracks))
        s = int(rng.integers(rt.dims.subs))
        rt.set_track(r, t, published=True, is_video=bool(rng.integers(2)))
        rt.set_subscription(r, t, s, subscribed=bool(rng.integers(2)))
        rt.set_layer_caps(r, t, s, max_spatial=int(rng.integers(3)),
                          max_temporal=int(rng.integers(4)))


async def test_ctrl_delta_upload_matches_full_upload():
    """Device meta/ctrl state after churn must be identical whether it
    went up as dirty-row deltas or full `_replace` uploads."""
    dims = plane.PlaneDims(rooms=8, tracks=2, pkts=4, subs=4)
    rt_delta = PlaneRuntime(dims, tick_ms=20)
    rt_full = PlaneRuntime(dims, tick_ms=20)
    rt_delta.ctrl_delta_max_rows = dims.rooms     # always delta
    rt_full.ctrl_delta_max_rows = 0               # any dirty row → full
    await rt_delta.step_once()                    # clear the init full flag
    await rt_full.step_once()
    for round_ in range(5):
        rng_a, rng_b = (np.random.default_rng(round_) for _ in range(2))
        _churn(rt_delta, rng_a)
        _churn(rt_full, rng_b)
        await rt_delta.step_once()
        await rt_full.step_once()
        for a, b in zip(rt_delta.state.meta, rt_full.state.meta):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(rt_delta.state.ctrl, rt_full.state.ctrl):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert rt_delta.stats["ctrl_delta_uploads"] >= 5
    assert rt_delta.stats["ctrl_full_uploads"] == 1   # only the init upload
    assert rt_full.stats["ctrl_full_uploads"] >= 6
    assert rt_full.stats["ctrl_delta_uploads"] == 0
    await rt_delta.stop()
    await rt_full.stop()


def test_delta_upload_is_o_dirty_rows_at_northstar_dims():
    """A subscription flip in ONE room ships O(dirty rows) bytes, not the
    O(R·T·S) full mirror. Pure numpy — pack_ctrl_rows at north-star dims
    without compiling (or allocating) anything on the device."""
    R, T, S = 10240, 8, 50
    meta = plane.TrackMeta(
        is_video=np.zeros((R, T), bool),
        published=np.zeros((R, T), bool),
        pub_muted=np.zeros((R, T), bool),
        is_svc=np.zeros((R, T), bool),
    )
    ctrl = plane.SubControl(
        subscribed=np.zeros((R, T, S), bool),
        sub_muted=np.zeros((R, T, S), bool),
        max_spatial=np.full((R, T, S), plane.MAX_LAYERS - 1, np.int32),
        max_temporal=np.full((R, T, S), 3, np.int32),
    )
    ctrl.subscribed[3, 1, 7] = True  # the flip
    rows, meta_rows, ctrl_rows = plane.pack_ctrl_rows(meta, ctrl, {3})
    assert list(rows) == [3]
    assert meta_rows.shape[1:] == (1, T) and ctrl_rows.shape[1:] == (1, T, S)
    full_bytes = sum(a.nbytes for a in meta) + sum(a.nbytes for a in ctrl)
    delta_bytes = meta_rows.nbytes + ctrl_rows.nbytes
    assert delta_bytes * 1000 < full_bytes  # 1 of 10240 rows, not all
    # Row payloads round-trip exactly.
    assert bool(ctrl_rows[0, 0, 1, 7])
    np.testing.assert_array_equal(ctrl_rows[0], ctrl.subscribed[[3]])


async def test_ctrl_upload_bytes_counter_tracks_delta():
    """The stats counter bills delta bytes, and a clean tick uploads
    nothing at all."""
    rt = PlaneRuntime(DIMS, tick_ms=20)
    await rt.step_once()                         # init full upload
    assert rt.stats["ctrl_full_uploads"] == 1
    base = rt.stats["ctrl_upload_bytes"]
    await rt.step_once()                         # clean: no upload
    assert rt.stats["ctrl_upload_bytes"] == base
    assert rt.stats["ctrl_delta_uploads"] == 0
    rt.set_subscription(0, 0, 1, subscribed=True)
    await rt.step_once()
    assert rt.stats["ctrl_delta_uploads"] == 1
    assert rt.stats["ctrl_delta_rows"] == 1
    assert rt.stats["ctrl_upload_bytes"] > base
    await rt.stop()


# -- double-buffered ingest staging sets ------------------------------------

def test_ingest_drain_flips_staging_sets():
    """drain() hands out one staging set and flips pushes to the other:
    consecutive drains alternate between exactly two array sets, and
    zero-copy (reuse_fields) header views stay intact while the next
    tick's pushes land in the sibling set."""
    buf = IngestBuffer(plane.PlaneDims(1, 1, 8, 1), tick_ms=10)
    buf.push(PacketIn(room=0, track=0, sn=100, ts=0, size=10, layer=1))
    set_a = buf.sn
    inp1, _ = buf.drain(reuse_fields=True)
    set_b = buf.sn
    assert set_b is not set_a                    # flipped to the sibling
    buf.push(PacketIn(room=0, track=0, sn=200, ts=0, size=10, layer=2))
    # Tick 1's zero-copy pack-only view is untouched by tick 2's push...
    assert int(inp1.layer[0, 0, 0]) == 1
    # ...and the munge-lifetime headers were copied outright.
    assert inp1.sn is not set_a
    assert int(inp1.sn[0, 0, 0]) == 100
    inp2, _ = buf.drain(reuse_fields=True)
    assert buf.sn is set_a                       # ping-pong: back to A
    assert int(inp2.sn[0, 0, 0]) == 200 and int(inp2.layer[0, 0, 0]) == 2


def test_ingest_retired_set_scrub_is_deferred():
    """The drained set is scrubbed lazily: scrub_retired() (called once
    the pipeline no longer needs the views) or the next flip onto it —
    never while tick N's pre-pack might still be reading it."""
    buf = IngestBuffer(plane.PlaneDims(1, 1, 8, 1), tick_ms=10)
    buf.push(PacketIn(room=0, track=0, sn=100, ts=0, size=10))
    inp1, _ = buf.drain(reuse_fields=True)
    retired = buf._sets[1 - buf._active]
    assert retired.needs_scrub and bool(retired.valid.any())
    buf.scrub_retired()
    assert not retired.needs_scrub
    assert not bool(retired.valid.any())         # masks cleared for reuse
    # Without an explicit scrub, the flip scrubs before rebinding: a
    # drain-drain sequence never resurrects tick N's packets as tick N+2's.
    buf.push(PacketIn(room=0, track=0, sn=101, ts=0, size=10))
    buf.drain(reuse_fields=True)
    inp3, _ = buf.drain(reuse_fields=True)       # no pushes: must be empty
    assert int(np.asarray(inp3.valid).sum()) == 0


def test_ingest_default_drain_copies_pack_fields():
    """reuse_fields=False (mesh path / direct callers): pack-only fields
    are real copies, safe to read after the set recycles."""
    buf = IngestBuffer(plane.PlaneDims(1, 1, 8, 1), tick_ms=10)
    buf.push(PacketIn(room=0, track=0, sn=100, ts=0, size=10, layer=1))
    set_a_layer = buf.layer
    inp, _ = buf.drain()
    assert inp.layer is not set_a_layer
    set_a_layer[:] = 9                            # scribble over the set
    assert int(inp.layer[0, 0, 0]) == 1


def test_payload_slab_survives_set_recycling():
    """PayloadSlab copies payload bytes out of the staging set: RTX
    replays reference slabs up to SLAB_WINDOW ticks old, far past the
    2-set ping-pong."""
    buf = IngestBuffer(plane.PlaneDims(1, 1, 8, 1), tick_ms=10)
    buf.push(PacketIn(room=0, track=0, sn=100, ts=0, size=3, payload=b"abc"))
    _, slab1 = buf.drain(reuse_fields=True)
    for i in range(4):  # recycle both sets twice over
        buf.push(PacketIn(room=0, track=0, sn=101 + i, ts=0, size=3,
                          payload=b"xyz"))
        buf.drain(reuse_fields=True)
        buf.scrub_retired()
    assert slab1.get(0, 0, 0)[0] == b"abc"
