"""Flight-recorder plane: trace-ring bounds, attribution sampling math,
black-box rings, and the Chrome trace-event export schema."""

from __future__ import annotations

import json

import numpy as np
import pytest

from livekit_server_tpu.runtime.trace import (
    EV_GOV_LEVEL,
    EV_NACK_STORM,
    EV_QUARANTINE,
    MAX_SHARDS,
    STAGES,
    BlackBox,
    LatencyAttribution,
    TickTraceRing,
)
from livekit_server_tpu.telemetry import trace_export


def _record(ring: TickTraceRing, idx: int, base: float = 100.0) -> int:
    """One well-formed tick record at a synthetic perf_counter base."""
    t = base + idx * 0.005
    return ring.record_tick(
        idx=idx, edge=t, stage_t0=t + 0.0001, stage_s=0.001,
        retier_s=0.0002, upload_t0=t + 0.0012, upload_s=0.0003,
        device_t0=t + 0.0016, device_s=0.002, fanout_t0=t + 0.0037,
        fanout_s=0.0008, send_s=0.0004, wake_over_us=42.0, depth=1,
        late=(idx % 7 == 0),
    )


# -- TickTraceRing ----------------------------------------------------------

def test_ring_bounds_and_wraparound():
    ring = TickTraceRing(cap=16)
    for i in range(40):
        _record(ring, i)
    assert ring.recorded == 40
    snap = ring.snapshot()
    # only the newest cap records survive, oldest first
    assert len(snap) == 16
    assert [r["tick"] for r in snap] == list(range(24, 40))


def test_ring_snapshot_newest_n():
    ring = TickTraceRing(cap=32)
    for i in range(10):
        _record(ring, i)
    snap = ring.snapshot(4)
    assert [r["tick"] for r in snap] == [6, 7, 8, 9]
    assert ring.snapshot(0) == []
    # n beyond what's recorded clamps
    assert len(ring.snapshot(99)) == 10


def test_ring_minimum_capacity():
    assert TickTraceRing(cap=1).cap >= 8


def test_ring_record_fields_round_trip():
    ring = TickTraceRing(cap=8)
    _record(ring, 3)
    r = ring.snapshot()[-1]
    assert r["tick"] == 3 and r["depth"] == 1
    assert r["stage_s"] == pytest.approx(0.001)
    assert r["retier_s"] == pytest.approx(0.0002)
    assert r["device_s"] == pytest.approx(0.002)
    assert r["wake_over_us"] == pytest.approx(42.0)


def test_ring_shard_lanes_bounded():
    ring = TickTraceRing(cap=8)
    slot = _record(ring, 0)
    ring.set_shard(slot, 0, 0.5, 0.25)
    ring.set_shard(slot, 2, 0.125, 0.0625)
    ring.set_shard(slot, MAX_SHARDS + 3, 9.0, 9.0)  # out of range: dropped
    r = ring.snapshot()[-1]
    assert len(r["shard_munge_ms"]) == 3  # lanes 0..2, lane 1 zero-filled
    assert r["shard_munge_ms"][0] == pytest.approx(0.5)
    assert r["shard_send_ms"][2] == pytest.approx(0.0625)


def test_ring_shard_reset_on_slot_reuse():
    ring = TickTraceRing(cap=8)
    slot = _record(ring, 0)
    ring.set_shard(slot, 5, 1.0, 1.0)
    for i in range(1, 9):  # wrap back onto slot 0
        _record(ring, i)
    r = ring.snapshot()[-1]
    assert r["tick"] == 8 and r["shard_munge_ms"] == []


# -- LatencyAttribution -----------------------------------------------------

def test_attribution_deterministic_sampling():
    la = LatencyAttribution(sample_every=8)
    sn = np.arange(32)
    ta = np.full(32, 99.0)
    la.observe_batch(sn, ta, t_dispatch=99.004, t_device_end=99.006,
                     now=99.010)
    # exactly sn % 8 == 0 sampled: 4 of 32
    assert int(la.total[STAGES.index("staging")]) == 4
    assert int(la.total[STAGES.index("total")]) == 4


def test_attribution_unstamped_and_predecomposition_batches_skipped():
    la = LatencyAttribution(sample_every=1)
    sn = np.arange(4)
    la.observe_batch(sn, np.zeros(4), 1.0, 2.0, 3.0)   # t_arr == 0
    la.observe_batch(sn, np.full(4, 99.0), 0.0, 0.0, 99.1)  # no stamps
    assert not la.summary()


def test_attribution_stage_split_sums_to_total():
    la = LatencyAttribution(sample_every=1)
    now = 200.0
    sn = np.array([0, 1, 2])
    ta = np.array([now - 0.010, now - 0.012, now - 0.008])
    la.observe_batch(sn, ta, t_dispatch=now - 0.006,
                     t_device_end=now - 0.004, now=now)
    d = la.drain()
    summed = d["staging"] + d["device"] + d["egress"]
    assert np.allclose(summed, d["total"], atol=1e-3)
    # late straggler (arrival after dispatch) clips staging at 0
    la.observe_batch(np.array([3]), np.array([now - 0.001]),
                     t_dispatch=now - 0.006, t_device_end=now - 0.004,
                     now=now)
    assert float(la.drain()["staging"][0]) == 0.0


def test_attribution_express_feeds_total_too():
    la = LatencyAttribution(sample_every=1)
    la.observe_express(np.array([0, 1]), np.array([9.998, 9.997]), 10.0)
    d = la.drain()
    assert len(d["express"]) == 2 and len(d["total"]) == 2
    assert "staging" not in d


def test_attribution_drain_is_incremental():
    la = LatencyAttribution(sample_every=1)
    la.observe_express(np.array([0]), np.array([0.9]), 1.0)
    assert len(la.drain()["express"]) == 1
    assert la.drain() == {}  # nothing new
    la.observe_express(np.array([1]), np.array([1.9]), 2.0)
    assert len(la.drain()["express"]) == 1


def test_attribution_ring_wrap_keeps_newest():
    la = LatencyAttribution(sample_every=1)
    n = la.CAP + 100
    la.observe_express(np.arange(n), np.full(n, 4.0), 5.0)
    d = la.drain()
    assert len(d["express"]) == la.CAP
    s = la.summary()
    # an over-CAP burst is truncated to the newest CAP before the push,
    # so the lifetime count reflects what was retained
    assert s["express"]["n"] == la.CAP
    assert s["express"]["p50_ms"] == pytest.approx(1000.0, rel=0.01)


def test_attribution_summary_percentiles():
    la = LatencyAttribution(sample_every=1)
    lat_s = np.arange(1, 101) / 1e3  # 1..100 ms
    la.observe_express(np.arange(100), 50.0 - lat_s, 50.0)
    s = la.summary()["express"]
    assert s["n"] == 100
    assert 49.0 <= s["p50_ms"] <= 52.0
    assert 98.0 <= s["p99_ms"] <= 100.0


# -- BlackBox ---------------------------------------------------------------

def test_blackbox_round_trip_and_bounds():
    bb = BlackBox(rooms=2, events=4)
    for k in range(7):
        bb.emit(1, EV_QUARANTINE, float(k))
    ev = bb.dump(1)
    assert len(ev) == 4  # ring keeps the last M
    assert [e["a"] for e in ev] == [3.0, 4.0, 5.0, 6.0]
    assert all(e["event"] == "quarantine" for e in ev)
    assert bb.dump(0) == []  # other lanes untouched


def test_blackbox_node_lane_and_out_of_range():
    bb = BlackBox(rooms=2, events=4)
    bb.emit(bb.NODE, EV_GOV_LEVEL, 0.0, 2.0)
    bb.emit(99, EV_GOV_LEVEL, 2.0, 3.0)  # out of range → node lane
    ev = bb.dump(bb.NODE)
    assert len(ev) == 2 and ev[0]["b"] == 2.0


def test_blackbox_dump_to_retains_and_logs():
    class Log:
        def __init__(self):
            self.calls = []

        def warn(self, msg, **kw):
            self.calls.append((msg, kw))

    log = Log()
    bb = BlackBox(rooms=1, events=4, log=log)
    bb.emit(0, EV_NACK_STORM, 1.0, 25.0)
    dumped = bb.dump_to(0, "nack_storm")
    assert dumped[-1]["event"] == "nack_storm"
    assert bb.dumps == 1
    assert bb.last_dumps[-1]["reason"] == "nack_storm"
    assert log.calls and log.calls[0][1]["room"] == 0
    # no log attached is fine (detached runtimes)
    bb.log = None
    bb.dump_to(0, "again")
    assert bb.dumps == 2


# -- export schema ----------------------------------------------------------

def _synthetic_events(n_ticks: int = 5):
    ring = TickTraceRing(cap=64)
    for i in range(n_ticks):
        slot = _record(ring, i)
        ring.set_shard(slot, 0, 0.2, 0.1)
        ring.set_shard(slot, 1, 0.15, 0.05)
    return trace_export.to_chrome(ring.snapshot(), tick_ms=5)


def test_export_schema_valid_and_json_clean():
    events = _synthetic_events()
    assert trace_export.validate(events) == []
    doc = json.loads(trace_export.export_json([], 5))
    assert doc["traceEvents"] == []


def test_export_span_inventory():
    events = _synthetic_events()
    names = {e["name"] for e in events}
    for want in ("tick_edge", "stage_host", "express_retier", "ctrl_upload",
                 "device_step", "fan_out", "egress_send", "munge", "send",
                 "thread_name"):
        assert want in names, want
    # every X event carries µs ts/dur and the shared pid
    for e in events:
        if e["ph"] == "X":
            assert e["pid"] == 1 and e["ts"] >= 0 and e["dur"] >= 0


def test_export_lane_assignment():
    events = _synthetic_events()
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], set()).add(e["tid"])
    assert by_name["stage_host"] == {trace_export.TID_LOOP}
    assert by_name["device_step"] == {trace_export.TID_DEVICE}
    assert by_name["fan_out"] == {trace_export.TID_FANOUT}
    assert by_name["munge"] == {trace_export.TID_SHARD0,
                                trace_export.TID_SHARD0 + 1}


def test_validate_rejects_broken_traces():
    assert trace_export.validate([{"ph": "X", "pid": 1, "tid": 1}])
    assert trace_export.validate(
        [{"name": "a", "ph": "X", "pid": 1, "tid": 1, "ts": 0.0,
          "dur": -1.0}]
    )
    # partial overlap on one lane is a nesting violation
    bad = [
        {"name": "a", "ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 10.0},
        {"name": "b", "ph": "X", "pid": 1, "tid": 1, "ts": 5.0, "dur": 10.0},
    ]
    assert any("overlaps" in p for p in trace_export.validate(bad))
    # containment is fine
    ok = [
        {"name": "a", "ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 10.0},
        {"name": "b", "ph": "X", "pid": 1, "tid": 1, "ts": 2.0, "dur": 3.0},
    ]
    assert trace_export.validate(ok) == []


def test_selftest_end_to_end():
    assert trace_export.selftest(ticks=4) == []
