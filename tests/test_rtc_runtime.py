"""In-process end-to-end: control plane → device plane → egress.

Mirrors the reference's integration tier (test/singlenode_test.go
TestSinglePublisher :140 — the behavioral spec of BASELINE.md config 1):
participants join a room through signal messages, publish tracks, media
packets flow through the batched plane, and subscribers receive munged
packets. No network; signal goes through MessageChannels, media through
IngestBuffer — the seams the WS/UDP transports plug into.
"""

import asyncio
import json

import pytest

from livekit_server_tpu.models import plane
from livekit_server_tpu.protocol import decode_signal_response
from livekit_server_tpu.protocol import models as pm
from livekit_server_tpu.protocol.signal import SignalRequest
from livekit_server_tpu.routing.messagechannel import MessageChannel
from livekit_server_tpu.rtc import Participant, Room, handle_participant_signal
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.ingest import PacketIn


DIMS = plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4)


def make_participant(room, identity, **kw):
    sink = MessageChannel(size=500)
    p = Participant(identity, room, response_sink=sink, **kw)
    return p, sink


def drain_sink(sink):
    out = []
    while True:
        try:
            out.append(decode_signal_response(sink._q.get_nowait()))
        except asyncio.QueueEmpty:
            return out
        except Exception:
            return out


def publish_audio(room, p, cid="mic1"):
    handle_participant_signal(room, p, SignalRequest("add_track", {"cid": cid, "type": 0, "name": "mic"}))
    track = p.publish_pending(cid)
    assert track is not None
    return track


@pytest.fixture
def runtime():
    return PlaneRuntime(DIMS, tick_ms=20)


async def test_two_party_audio_end_to_end(runtime):
    room = Room("lobby", runtime)
    alice, a_sink = make_participant(room, "alice")
    bob, b_sink = make_participant(room, "bob")
    join_a = room.join(alice)
    join_b = room.join(bob)
    assert join_a["room"]["name"] == "lobby"
    assert join_b["other_participants"][0]["identity"] == "alice"

    track = publish_audio(room, alice)
    # track_published went to alice; bob got auto-subscribed
    kinds_a = [m.kind for m in drain_sink(a_sink)]
    assert "track_published" in kinds_a
    kinds_b = [m.kind for m in drain_sink(b_sink)]
    assert "track_subscribed" in kinds_b

    # media: bob registers egress, alice publishes 3 loud packets
    got = []
    bob.on_media(got.append)
    for i in range(3):
        runtime.ingest.push(
            PacketIn(
                room=room.slots.row, track=track.track_col,
                sn=7000 + i, ts=960 * i, size=120, payload=bytes([i]) * 10,
                audio_level=18, frame_ms=20,
            )
        )
        res = await runtime.step_once()
        for pkt in res.egress:
            room.deliver_egress(pkt)
    assert [p.sn for p in got] == [7000, 7001, 7002]
    assert got[0].payload == b"\x00" * 10
    assert all(p.sub == bob.sub_col for p in got)


async def test_active_speaker_broadcast(runtime):
    room = Room("spk", runtime)
    alice, a_sink = make_participant(room, "alice")
    bob, b_sink = make_participant(room, "bob")
    room.join(alice)
    room.join(bob)
    track = publish_audio(room, alice)
    # 600 ms of loud audio from alice (30 ticks × 20 ms)
    for i in range(30):
        runtime.ingest.push(
            PacketIn(room=room.slots.row, track=track.track_col,
                     sn=i, ts=960 * i, size=100, audio_level=15, frame_ms=20)
        )
        res = await runtime.step_once()
        if room.slots.row in res.speakers:
            room.handle_speakers(res.speakers[room.slots.row])
    msgs = [m for m in drain_sink(b_sink) if m.kind == "speakers_changed"]
    assert msgs, "no speakers_changed broadcast"
    assert msgs[-1].data["speakers"][0]["sid"] == alice.sid


async def test_mute_stops_forwarding(runtime):
    room = Room("mute", runtime)
    alice, _ = make_participant(room, "alice")
    bob, _ = make_participant(room, "bob")
    room.join(alice)
    room.join(bob)
    track = publish_audio(room, alice)
    got = []
    bob.on_media(got.append)

    handle_participant_signal(room, alice, SignalRequest("mute", {"sid": track.info.sid, "muted": True}))
    runtime.ingest.push(
        PacketIn(room=room.slots.row, track=track.track_col, sn=1, ts=0, size=50)
    )
    res = await runtime.step_once()
    for pkt in res.egress:
        room.deliver_egress(pkt)
    assert got == []

    handle_participant_signal(room, alice, SignalRequest("mute", {"sid": track.info.sid, "muted": False}))
    runtime.ingest.push(
        PacketIn(room=room.slots.row, track=track.track_col, sn=2, ts=960, size=50)
    )
    res = await runtime.step_once()
    for pkt in res.egress:
        room.deliver_egress(pkt)
    assert [p.sn for p in got] == [2]


async def test_unsubscribe_and_permissions(runtime):
    room = Room("perm", runtime)
    alice, _ = make_participant(room, "alice")
    bob, b_sink = make_participant(room, "bob")
    room.join(alice)
    room.join(bob)
    track = publish_audio(room, alice)
    # bob explicitly unsubscribes
    handle_participant_signal(
        room, bob, SignalRequest("subscription", {"track_sids": [track.info.sid], "subscribe": False})
    )
    got = []
    bob.on_media(got.append)
    runtime.ingest.push(PacketIn(room=room.slots.row, track=track.track_col, sn=1, ts=0, size=50))
    res = await runtime.step_once()
    for pkt in res.egress:
        room.deliver_egress(pkt)
    assert got == []

    # a participant without can_subscribe is refused
    carol, c_sink = make_participant(
        room, "carol", grants={"video": {"canSubscribe": False}}
    )
    room.join(carol)
    assert not room.subscribe(carol, track.info.sid)
    kinds = [m.kind for m in drain_sink(c_sink)]
    assert "subscription_response" in kinds


async def test_subscription_permission_per_track(runtime):
    """livekit.TrackPermission semantics: an entry listing track_sids grants
    ONLY those tracks; an entry with no track_sids grants all (the pooled
    reading — every allowed identity gets every track — is a privilege
    escalation; see uptrackmanager.go subscription permissions)."""
    room = Room("tperm", runtime)
    alice, _ = make_participant(room, "alice")
    bob, _ = make_participant(room, "bob")
    carol, _ = make_participant(room, "carol")
    room.join(alice)
    room.join(bob)
    room.join(carol)
    t1 = publish_audio(room, alice, cid="mic1")
    t2 = publish_audio(room, alice, cid="mic2")
    assert t1.info.sid in bob.subscribed_tracks  # auto-subscribed pre-restriction
    # alice restricts: bob may see only t1; carol keeps everything
    handle_participant_signal(
        room,
        alice,
        SignalRequest(
            "subscription_permission",
            {
                "track_permissions": [
                    {"participant_identity": "bob", "track_sids": [t1.info.sid]},
                    {"participant_identity": "carol"},
                ]
            },
        ),
    )
    assert t1.info.sid in bob.subscribed_tracks
    assert t2.info.sid not in bob.subscribed_tracks
    assert t1.info.sid in carol.subscribed_tracks
    assert t2.info.sid in carol.subscribed_tracks


async def test_join_capacity_rejection(runtime):
    """Sub-column exhaustion raises CapacityError (the session layer turns
    it into an explicit JOIN_FAILURE leave, not a silent hang)."""
    from livekit_server_tpu.runtime import CapacityError

    room = Room("full", runtime)
    joined = []
    for i in range(DIMS.subs):
        p, _ = make_participant(room, f"p{i}")
        room.join(p)
        joined.append(p)
    extra, _ = make_participant(room, "overflow")
    with pytest.raises(CapacityError):
        room.join(extra)


async def test_duplicate_identity_kicks_old(runtime):
    room = Room("dup", runtime)
    a1, s1 = make_participant(room, "alice")
    room.join(a1)
    a2, s2 = make_participant(room, "alice")
    room.join(a2)
    assert a1.state == pm.ParticipantState.DISCONNECTED
    assert a1.close_reason == pm.DisconnectReason.DUPLICATE_IDENTITY
    assert room.participants["alice"] is a2
    assert len(room.participants) == 1


async def test_leave_and_idle_close(runtime):
    room = Room("bye", runtime)
    room.info.empty_timeout = 0
    room.info.departure_timeout = 0  # post-departure reaping governs here
    alice, _ = make_participant(room, "alice")
    room.join(alice)
    handle_participant_signal(room, alice, SignalRequest("leave", {}))
    assert room.is_empty
    import time
    assert room.should_close(now=time.time() + 1)
    room.close()
    assert runtime.slots.get("bye") is None
    # row is reusable
    room2 = Room("bye2", runtime)
    assert room2.slots.row == room.slots.row


async def test_data_broadcast(runtime):
    room = Room("data", runtime)
    alice, _ = make_participant(room, "alice")
    bob, b_sink = make_participant(room, "bob")
    room.join(alice)
    room.join(bob)
    room.broadcast_data(alice, payload="aGVsbG8=", kind=1, topic="chat")
    msgs = [m for m in drain_sink(b_sink) if m.kind == "data_packet"]
    assert msgs and msgs[0].data["payload"] == "aGVsbG8="
    assert msgs[0].data["topic"] == "chat"


async def test_ping_pong_and_metadata(runtime):
    room = Room("misc", runtime)
    alice, a_sink = make_participant(
        room, "alice", grants={"video": {"canUpdateOwnMetadata": True}}
    )
    room.join(alice)
    handle_participant_signal(room, alice, SignalRequest("ping", {"timestamp": 123}))
    msgs = drain_sink(a_sink)
    pongs = [m for m in msgs if m.kind == "pong"]
    assert pongs and pongs[0].data["last_ping_timestamp"] == 123

    handle_participant_signal(
        room, alice, SignalRequest("update_metadata", {"metadata": "m2", "name": "Alice"})
    )
    assert alice.metadata == "m2" and alice.name == "Alice"


async def test_connection_quality_signal(runtime):
    """handle_quality broadcasts per-participant connection_quality built
    from device scores (room.go:1318 connectionQualityWorker)."""
    import numpy as np

    room = Room("q", runtime)
    alice, a_sink = make_participant(room, "alice")
    bob, b_sink = make_participant(room, "bob")
    room.join(alice)
    room.join(bob)
    track = publish_audio(room, alice)
    col = track.track_col

    track_quality = np.full((DIMS.tracks,), 3, np.int32)
    track_quality[col] = 2
    track_mos = np.full((DIMS.tracks,), 1.0, np.float32)
    track_mos[col] = 4.4
    sub_quality = np.full((DIMS.subs,), 2, np.int32)
    room.handle_quality(track_quality, track_mos, sub_quality)

    msgs = [m for m in drain_sink(b_sink) if m.kind == "connection_quality"]
    assert msgs, "no connection_quality broadcast"
    updates = {u["participant_sid"]: u for u in msgs[-1].data["updates"]}
    assert updates[alice.sid]["quality"] == 2
    assert updates[alice.sid]["score"] == 4.4
    # bob publishes nothing; his quality comes from the subscriber side
    assert updates[bob.sid]["quality"] == 2


async def test_quality_window_rolls_in_runtime(runtime):
    """The runtime closes the stats window about once a second and carries
    quality tensors in TickResult."""
    closed = 0
    for _ in range(1000 // runtime.tick_ms + 1):
        res = await runtime.step_once()
        closed += res.quality_window_closed
    assert closed >= 1
    assert res.track_quality is not None
    assert res.track_quality.shape == (DIMS.rooms, DIMS.tracks)


async def test_publisher_rtt_feeds_track_mos(runtime):
    """The measured publisher-path RTT (ingest.rtt_ms via the track→
    publisher-slot mapping) reaches the device E-model: identical clean
    streams score worse on a high-RTT publisher path."""
    runtime.set_track(0, 0, published=True, is_video=False, pub_sub=1)
    runtime.set_track(0, 1, published=True, is_video=False, pub_sub=2)
    runtime.set_subscription(0, 0, 3, subscribed=True)
    runtime.set_subscription(0, 1, 3, subscribed=True)
    runtime.ingest.set_rtt(0, 1, 600)   # track 0's publisher: bad path
    runtime.ingest.set_rtt(0, 2, 1)     # track 1's publisher: pristine
    res = None
    for i in range(12):
        for t in (0, 1):
            runtime.ingest.push(PacketIn(
                room=0, track=t, sn=100 + i, ts=960 * i, size=120,
                payload=b"x" * 120,
            ))
        res = await runtime.step_once()
    mos_hi_rtt = float(res.track_mos[0, 0])
    mos_lo_rtt = float(res.track_mos[0, 1])
    assert mos_hi_rtt < mos_lo_rtt - 0.2, (mos_hi_rtt, mos_lo_rtt)


async def test_dynacast_subscribed_quality_update(runtime):
    """Subscriber caps aggregate to a subscribed_quality_update for the
    publisher; upgrades fire immediately (dynacastmanager.go:187-255)."""
    room = Room("dyn", runtime)
    alice, a_sink = make_participant(room, "alice")
    bob, _ = make_participant(room, "bob")
    room.join(alice)
    room.join(bob)
    handle_participant_signal(
        room, alice,
        SignalRequest("add_track", {"cid": "cam", "type": 1, "name": "v"}),
    )
    track = alice.publish_pending("cam")
    assert track is not None
    # bob (the only subscriber) caps the track at quality 0
    room.update_track_settings(bob, track.info.sid, {"quality": 0})
    room.reconcile_dynacast()
    msgs = [m for m in drain_sink(a_sink) if m.kind == "subscribed_quality_update"]
    assert msgs
    upd = msgs[-1].data
    assert upd["track_sid"] == track.info.sid
    enabled = {q["quality"]: q["enabled"] for q in upd["subscribed_qualities"]}
    assert enabled == {0: True, 1: False, 2: False}

    # raising the cap re-enables layers immediately (no debounce on up)
    room.update_track_settings(bob, track.info.sid, {"quality": 2})
    room.reconcile_dynacast()
    msgs = [m for m in drain_sink(a_sink) if m.kind == "subscribed_quality_update"]
    assert msgs
    enabled = {q["quality"]: q["enabled"] for q in msgs[-1].data["subscribed_qualities"]}
    assert enabled == {0: True, 1: True, 2: True}


def test_ingest_reorders_within_tick():
    """Out-of-order arrivals inside one tick are sorted by SN before the
    device sees them (buffer.Buffer jitter ordering, buffer.go Write)."""
    from livekit_server_tpu.models import plane as plane_mod
    from livekit_server_tpu.runtime.ingest import IngestBuffer

    buf = IngestBuffer(plane_mod.PlaneDims(1, 2, 8, 2), tick_ms=10)
    for sn in (102, 100, 103, 101):
        buf.push(PacketIn(room=0, track=0, sn=sn, ts=sn * 10, size=10,
                          payload=bytes([sn & 0xFF])))
    inp, slab = buf.drain()
    valid = inp.valid[0, 0]
    assert list(inp.sn[0, 0][valid]) == [100, 101, 102, 103]
    # Payload slab indices permuted consistently with the header fields.
    assert slab.get(0, 0, 0)[0] == bytes([100])
    assert slab.get(0, 0, 3)[0] == bytes([103])


def test_ingest_reorder_handles_sn_wrap():
    from livekit_server_tpu.models import plane as plane_mod
    from livekit_server_tpu.runtime.ingest import IngestBuffer

    buf = IngestBuffer(plane_mod.PlaneDims(1, 1, 4, 1), tick_ms=10)
    for sn in (1, 65535, 0, 2):  # wraps 65535 → 0 → 1 → 2
        buf.push(PacketIn(room=0, track=0, sn=sn, ts=0, size=10))
    inp, _ = buf.drain()
    assert list(inp.sn[0, 0][inp.valid[0, 0]]) == [65535, 0, 1, 2]


def test_ingest_dedups_within_tick():
    from livekit_server_tpu.models import plane as plane_mod
    from livekit_server_tpu.runtime.ingest import IngestBuffer

    buf = IngestBuffer(plane_mod.PlaneDims(1, 1, 8, 1), tick_ms=10)
    for sn in (100, 101, 101, 102, 101):
        buf.push(PacketIn(room=0, track=0, sn=sn, ts=0, size=10))
    inp, _ = buf.drain()
    assert int(inp.valid.sum()) == 3
    assert buf.dupes == 2
    assert sorted(inp.sn[0, 0][inp.valid[0, 0]]) == [100, 101, 102]


def test_ingest_reorder_is_per_layer():
    """Simulcast layers have independent SN spaces; ordering must group by
    layer, not interleave across spaces."""
    from livekit_server_tpu.models import plane as plane_mod
    from livekit_server_tpu.runtime.ingest import IngestBuffer

    buf = IngestBuffer(plane_mod.PlaneDims(1, 1, 8, 1), tick_ms=10)
    buf.push(PacketIn(room=0, track=0, sn=5000, ts=0, size=10, layer=1))
    buf.push(PacketIn(room=0, track=0, sn=101, ts=0, size=10, layer=0))
    buf.push(PacketIn(room=0, track=0, sn=5001, ts=0, size=10, layer=1))
    buf.push(PacketIn(room=0, track=0, sn=100, ts=0, size=10, layer=0))
    inp, _ = buf.drain()
    v = inp.valid[0, 0]
    pairs = list(zip(inp.layer[0, 0][v], inp.sn[0, 0][v]))
    assert pairs == [(0, 100), (0, 101), (1, 5000), (1, 5001)]


async def test_bwe_probe_recovers_estimate(runtime):
    """Induced congestion drops the committed budget; once the channel is
    clear, the probe controller pads toward a goal and a goal-level
    estimate sample recovers the budget — no waiting for organic samples
    (probe_controller.go:33-295 + WritePaddingRTP)."""
    import numpy as np

    r, t, s = 0, 0, 1
    runtime.set_track(r, t, published=True, is_video=True)
    runtime.set_subscription(r, t, s, subscribed=True)

    def push_video(i, size=1100):
        # Periodic keyframes: the selector locks onto a layer only at a
        # keyframe, like a real publisher answering PLIs.
        kf = i % 5 == 0
        runtime.ingest.push(PacketIn(
            room=r, track=t, sn=2000 + i, ts=3000 * i, size=size,
            payload=b"v" * 40, layer=0, keyframe=kf,
            layer_sync=kf, begin_pic=True, frame_ms=0,
        ))

    # Warm up: traffic + healthy estimates → measured bitrates, high budget.
    i = 0
    for _ in range(10):
        push_video(i); i += 1
        runtime.ingest.push_feedback(r, s, estimate=5_000_000.0)
        await runtime.step_once()

    # Congest: steeply declining estimates (trend < 0) under load.
    for est in np.linspace(4_000_000, 120_000, 12):
        push_video(i); i += 1
        runtime.ingest.push_feedback(r, s, estimate=float(est))
        res = await runtime.step_once()
    assert s in res.congested.get(r, []), "congestion never detected"
    low_budget = runtime._last_committed[r, s]
    assert low_budget < 1_000_000

    # Clear channel, deficient allocation (video bps > budget): the probe
    # controller must start padding on its own.
    padded = []
    for _ in range(80):
        push_video(i); i += 1
        res = await runtime.step_once()
        padded.extend(res.padding)
        if padded:
            break
    assert padded, "probe controller never started padding"
    assert all(p.padding and p.sub == s and p.room == r for p in padded)
    goal = runtime.prober.goal[r, s]
    assert goal >= low_budget * 1.4

    # The probed client answers each probe with a goal-level estimate;
    # successive probe rounds ladder the budget up (320k → 480k → …)
    # until the 440 kbps track fits and forwarding resumes — recovery
    # driven entirely by probing, not organic estimate growth.
    real = []
    for _ in range(400):
        push_video(i); i += 1
        if runtime.prober.state[r, s] == 1:  # client "sees" the padding
            runtime.ingest.push_feedback(
                r, s, estimate=float(runtime.prober.goal[r, s])
            )
        res = await runtime.step_once()
        padded.extend(res.padding)
        real += [p for p in res.egress if p.sub == s and p.room == r]
        if real:
            break
    assert runtime.prober.stats["succeeded"] >= 1
    assert runtime._last_committed[r, s] > 440_000, "budget never recovered"
    assert real, "forwarding never resumed after probe recovery"

    # Padding advanced the munged SN space: real packets forwarded after
    # the padding runs continue beyond their SNs (no SN reuse).
    pad_sns = [p.sn for p in padded]
    assert all(p.sn > max(pad_sns) for p in real)


async def test_checkpoint_restore_mid_stream(runtime):
    """Munger state survives snapshot/restore (migration seeding, §5.4)."""
    room = Room("ckpt", runtime)
    alice, _ = make_participant(room, "alice")
    bob, _ = make_participant(room, "bob")
    room.join(alice)
    room.join(bob)
    track = publish_audio(room, alice)
    got = []
    bob.on_media(got.append)
    for i in range(3):
        runtime.ingest.push(
            PacketIn(room=room.slots.row, track=track.track_col, sn=100 + i, ts=960 * i, size=50)
        )
        res = await runtime.step_once()
        for pkt in res.egress:
            room.deliver_egress(pkt)
    snap = runtime.snapshot()
    runtime.restore(snap)
    runtime.ingest.push(
        PacketIn(room=room.slots.row, track=track.track_col, sn=103, ts=960 * 3, size=50)
    )
    res = await runtime.step_once()
    for pkt in res.egress:
        room.deliver_egress(pkt)
    assert [p.sn for p in got] == [100, 101, 102, 103]


async def test_stream_state_update_on_pause_and_resume(runtime):
    """Allocator pause transitions reach subscribers as stream_state_update
    (streamallocator.go StreamStateUpdate → signal relay): capping a sub's
    layers to nothing pauses the stream; restoring them resumes it. Only
    transitions are signaled."""
    room = Room("ssu", runtime)
    alice, _ = make_participant(room, "alice")
    bob, b_sink = make_participant(room, "bob")
    room.join(alice)
    room.join(bob)
    handle_participant_signal(
        room, alice,
        SignalRequest("add_track", {"cid": "cam", "type": 1, "name": "c",
                                    "layers": [{"quality": 0}, {"quality": 1}]}),
    )
    track = alice.publish_pending("cam")
    assert track is not None and track.is_video
    sid = track.info.sid
    room.subscribe(bob, sid)

    sn = [100]

    async def window():
        # live traffic each tick (a silent track allocates as paused),
        # then a quality-window dispatch with fresh targets
        for _ in range(3):
            for _k in range(2):
                runtime.ingest.push(PacketIn(
                    room=room.slots.row, track=track.track_col, sn=sn[0],
                    ts=sn[0] * 3000, size=900, payload=b"x" * 900,
                    layer=0, keyframe=sn[0] == 100, layer_sync=True,
                ))
                sn[0] += 1
            res = await runtime.step_once()
        return res

    res = await window()
    room.update_stream_states(res.target_layers[room.slots.row])
    drain_sink(b_sink)  # initial active is implicit — nothing asserted here

    # Cap to nothing → allocator target -1 → paused.
    runtime.set_layer_caps(room.slots.row, track.track_col, bob.sub_col,
                           max_spatial=-1, max_temporal=-1)
    res = await window()
    room.update_stream_states(res.target_layers[room.slots.row])
    msgs = [m for m in drain_sink(b_sink) if m.kind == "stream_state_update"]
    assert msgs and msgs[-1].data["stream_states"] == [
        {"track_sid": sid, "state": "paused"}
    ]

    # Same state again → no repeat signal.
    res = await window()
    room.update_stream_states(res.target_layers[room.slots.row])
    assert not [m for m in drain_sink(b_sink) if m.kind == "stream_state_update"]

    # Restore caps → active transition.
    runtime.set_layer_caps(room.slots.row, track.track_col, bob.sub_col,
                           max_spatial=2, max_temporal=3)
    res = await window()
    room.update_stream_states(res.target_layers[room.slots.row])
    msgs = [m for m in drain_sink(b_sink) if m.kind == "stream_state_update"]
    assert msgs and msgs[-1].data["stream_states"] == [
        {"track_sid": sid, "state": "active"}
    ]


async def test_full_grid_burst_forwards_without_caps():
    """The bit-packed mask egress has no capacity limit to overflow: a
    full-grid burst (every packet to every subscriber) forwards complete
    on the FIRST tick, with no recompiles and no drops. (Replaces the r4
    egress-cap auto-widening test — the cap itself is gone with the
    decide-on-device/rewrite-on-host split.)"""
    dims = plane.PlaneDims(rooms=1, tracks=2, pkts=4, subs=8)
    rt = PlaneRuntime(dims, tick_ms=10)

    def burst():
        for t in range(2):
            for k in range(4):
                rt.ingest.push(PacketIn(
                    room=0, track=t, sn=100 + k + t * 50, ts=960 * k,
                    size=60, payload=b"x" * 60,
                ))

    for t in range(2):
        rt.set_track(0, t, published=True, is_video=False)
        for s in range(8):
            rt.set_subscription(0, t, s, subscribed=True)
    burst()
    res = await rt.step_once()
    assert len(res.egress_batch) == 64  # 2 tracks × 4 pkts × 8 subs, tick 1
    burst()
    res = await rt.step_once()
    assert len(res.egress_batch) == 64
    await rt.stop()


async def test_low_latency_loop_delivers_and_stops_clean():
    """plane.low_latency: the serving loop completes each tick's fan-out
    in-tick (egress leaves within the period); a stop() issued while
    packets are still streaming must not duplicate any send or advance
    host munger offsets twice (the cancellation drain must not
    re-complete a tick whose fan-out already ran). The stop lands
    mid-stream — after some but not necessarily all deliveries — so the
    drain path runs with a packet-bearing tick plausibly in flight;
    uniqueness and munger-consistency asserts check whatever arrived."""
    dims = plane.PlaneDims(1, 2, 4, 2)
    rt = PlaneRuntime(dims, tick_ms=10, low_latency=True)
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    seen = []
    rt.on_tick(lambda res: seen.append(res.egress_batch))
    rt.start()
    try:
        # Warm: the first tick pays the jit compile, which spans many tick
        # periods — pushing during it would overflow the K packet slots.
        deadline = asyncio.get_event_loop().time() + 60.0
        while rt.stats["ticks"] < 1:
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError("first tick never completed")
            await asyncio.sleep(0.02)
        for i in range(6):
            rt.ingest.push(PacketIn(room=0, track=0, sn=500 + i, ts=960 * i,
                                    size=40, payload=b"z" * 40))
            await asyncio.sleep(0.02)
        # Wait for PARTIAL delivery only, then stop mid-stream: the
        # cancellation drain runs while later packet-bearing ticks are
        # still in flight.
        deadline = asyncio.get_event_loop().time() + 5.0
        while sum(len(b) for b in seen) < 2:
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"only {sum(len(b) for b in seen)} sends")
            await asyncio.sleep(0.01)
    finally:
        await rt.stop()
    import numpy as np

    sns = sorted(
        int(sn) & 0xFFFF for b in seen for sn in np.asarray(b.sn)
    )
    # Whatever arrived, arrived exactly ONCE, in SN order from 500 (a
    # double-run fan-out at stop would duplicate an SN).
    assert len(sns) >= 2
    assert sns == [500 + i for i in range(len(sns))]
    # Munger state advanced exactly once per DELIVERED packet: last_sn of
    # the (track 0, sub 1) lane is the last delivered SN (a re-completed
    # tick would have advanced it past — or doubled — this).
    assert int(rt.munger.last_sn[0, 0, 1]) == sns[-1]
