"""RED (RFC 2198) audio redundancy + playout-delay extension end-to-end.

Reference parity: pkg/sfu/redreceiver.go (primary → RED encapsulation for
RED subscribers), redprimaryreceiver.go (RED publisher → primary decap),
and pkg/sfu/rtpextension/playoutdelay.go (min/max playout-delay header
extension on video egress).
"""

import asyncio
import socket

import numpy as np

from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.udp import (
    OPUS_PT,
    PLAYOUT_DELAY_EXT_ID,
    RED_PT,
    start_udp_transport,
)
from tests.test_native import rtp_packet, vp8_payload

DIMS = plane.PlaneDims(rooms=1, tracks=4, pkts=8, subs=4)


def parse_red(payload: bytes):
    """→ (blocks [(pt, ts_off, bytes)], primary_bytes)."""
    q = 0
    hdrs = []
    while payload[q] & 0x80:
        pt = payload[q] & 0x7F
        off = (payload[q + 1] << 6) | (payload[q + 2] >> 2)
        ln = ((payload[q + 2] & 0x03) << 8) | payload[q + 3]
        hdrs.append((pt, off, ln))
        q += 4
    prim_pt = payload[q] & 0x7F
    q += 1
    blocks = []
    for pt, off, ln in hdrs:
        blocks.append((pt, off, payload[q : q + ln]))
        q += ln
    return blocks, payload[q:], prim_pt


async def _setup(tick_ms=10):
    runtime = PlaneRuntime(DIMS, tick_ms=tick_ms)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    return runtime, transport, port


async def test_red_encapsulation_toggles_per_subscriber():
    runtime, transport, port = await _setup()
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)   # RED sub
        runtime.set_subscription(0, 0, 2, subscribed=True)   # plain sub
        ssrc = transport.assign_ssrc(0, 0, is_video=False)
        transport.set_sub_red(0, 1, True)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        socks = {}
        for col in (1, 2):
            ss = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            ss.bind(("127.0.0.1", 0))
            ss.setblocking(False)
            socks[col] = ss
            transport.register_subscriber(0, col, ss.getsockname())

        payloads = [b"opus-frame-%d" % i for i in range(6)]
        got = {1: [], 2: []}
        for i, pay in enumerate(payloads):
            pub.sendto(
                rtp_packet(sn=100 + i, ts=960 * i, ssrc=ssrc, pt=OPUS_PT,
                           audio_level=30, payload=pay),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress_batch(
                res.egress_batch, red_plan=(res.red_sn, res.red_off, res.red_ok)
            )
            await asyncio.sleep(0.01)
            for col, ss in socks.items():
                while True:
                    try:
                        d = ss.recvfrom(4096)[0]
                        if not 192 <= d[1] <= 223:
                            got[col].append(d)
                    except BlockingIOError:
                        break

        assert len(got[1]) >= 5 and len(got[2]) >= 5
        # Plain subscriber: normal Opus PT, raw payload.
        for d in got[2]:
            assert d[1] & 0x7F == OPUS_PT
        assert any(p in d for p in payloads for d in got[2])
        # RED subscriber: RED PT; primary == original; later packets carry
        # redundancy blocks with the PREVIOUS payloads.
        saw_redundancy = False
        for d in got[1]:
            assert d[1] & 0x7F == RED_PT
            blocks, prim, prim_pt = parse_red(d[12:])
            assert prim_pt == OPUS_PT
            assert prim in payloads
            for pt, off, blk in blocks:
                assert pt == OPUS_PT and blk in payloads and off > 0
                # redundancy precedes its primary
                assert payloads.index(blk) < payloads.index(prim)
                saw_redundancy = True
        assert saw_redundancy, "no RED packet carried a redundancy block"
        pub.close()
        for ss in socks.values():
            ss.close()
    finally:
        transport.transport.close()
        await runtime.stop()


async def test_red_publisher_decap():
    """A RED-publishing client's packets are stripped to the primary block
    before staging (redprimaryreceiver.go)."""
    runtime, transport, port = await _setup()
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(0, 0, is_video=False)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        prev = b"previous-opus"
        prim = b"current-opus!"
        # RED payload: one redundancy block (prev, off 960) + primary.
        red = bytes([0x80 | OPUS_PT, 960 >> 6, ((960 & 0x3F) << 2) | 0,
                     len(prev)]) + bytes([OPUS_PT]) + prev + prim
        got = []
        for i in range(4):
            pub.sendto(
                rtp_packet(sn=300 + i, ts=960 * (i + 1), ssrc=ssrc, pt=RED_PT,
                           payload=red),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress_batch(res.egress_batch)
            await asyncio.sleep(0.01)
            while True:
                try:
                    d = sub.recvfrom(4096)[0]
                    if not 192 <= d[1] <= 223:
                        got.append(d)
                except BlockingIOError:
                    break
        assert transport.stats.get("red_rx", 0) >= 4
        assert got, "no forwarded packets"
        for d in got:
            assert d[12:] == prim        # primary only; RED shell stripped
        pub.close()
        sub.close()
    finally:
        transport.transport.close()
        await runtime.stop()


async def test_playout_delay_extension_on_video_egress():
    runtime, transport, port = await _setup()
    try:
        transport.playout_delay = (100, 400)  # ms
        runtime.set_track(0, 0, published=True, is_video=True)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(0, 0, is_video=True)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        got = []
        for i in range(10):
            pub.sendto(
                rtp_packet(sn=500 + i, ts=3000 * i, ssrc=ssrc, pt=96,
                           payload=vp8_payload(pid=100 + i, tl0=1, tid=0,
                                               keyframe=True)),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress_batch(res.egress_batch)
            await asyncio.sleep(0.01)
            while True:
                try:
                    d = sub.recvfrom(4096)[0]
                    if not 192 <= d[1] <= 223:
                        got.append(d)
                except BlockingIOError:
                    break
        assert got, "no forwarded video"
        for d in got:
            assert d[0] & 0x10, "X bit missing"
            assert d[12:14] == b"\xbe\xde"
            ext_words = int.from_bytes(d[14:16], "big")
            assert ext_words == 1
            assert d[16] >> 4 == PLAYOUT_DELAY_EXT_ID
            assert d[16] & 0x0F == 2  # 3-byte value
            val = int.from_bytes(d[17:20], "big")
            assert val >> 12 == 100 // 10 and val & 0xFFF == 400 // 10
        pub.close()
        sub.close()
    finally:
        transport.transport.close()
        await runtime.stop()
