"""Ragged-aware pooled-tick kernel (ops/paged_kernel.py + the live-
extent path in models/paged.py): the freeze-the-dead invariant the live
path relies on, model-level bit-parity of the fused tick (CPU fallback
AND Pallas interpret mode) against the stock pooled tick, page-local mix
parity against ops/mix.py, the runtime acceptance gate (paged_kernel
="interpret" vs "off" through grow-on-join and a compaction move), the
grid-steps ∝ live-pages accounting, the zero-live-pages tick, and the
`plane.paged_kernel` config knob."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from livekit_server_tpu.config import ConfigError, load_config
from livekit_server_tpu.models import paged, plane
from livekit_server_tpu.ops import mix, paged_kernel
from livekit_server_tpu.runtime.ingest import PacketIn
from livekit_server_tpu.runtime.paged_runtime import PagedPlaneRuntime

PD = paged.PagedDims(rooms=4, tracks=4, pkts=4, subs=8,
                     tpage=2, spage=4, pool_pages=16)

# -- shared model-level fixture: hand-built page table -----------------------
# room 0 = one page (tp0, sp0); room 1 = the full 2x2 grid. 5 live pages,
# 11 dead, live_rows padded to the pow2 bucket of 8 with a LIVE row.


def _table_and_rows():
    P, MT = PD.pool_pages, PD.max_tpages
    pg_room = np.full(P, -1, np.int32)
    pg_tp = np.full(P, -1, np.int32)
    pg_sp = np.full(P, -1, np.int32)
    tmembers = np.full((P, MT), -1, np.int32)
    pg_room[0], pg_tp[0], pg_sp[0] = 0, 0, 0
    tmembers[0] = [0, -1]
    grid = {(0, 0): 1, (1, 0): 2, (0, 1): 3, (1, 1): 4}
    for (tp, sp), pid in grid.items():
        pg_room[pid], pg_tp[pid], pg_sp[pid] = 1, tp, sp
    for sp in range(2):
        row = [grid[(0, sp)], grid[(1, sp)]]
        for tp in range(2):
            tmembers[grid[(tp, sp)]] = row
    table = paged.PageTable(
        rooms_pages=jnp.full((PD.rooms, MT * PD.max_spages), -1, jnp.int32),
        tmembers=jnp.asarray(tmembers),
        pg_room=jnp.asarray(pg_room),
        pg_tp=jnp.asarray(pg_tp),
        pg_sp=jnp.asarray(pg_sp),
    )
    live = np.where(pg_room >= 0)[0].astype(np.int32)
    live_rows = np.concatenate(
        [live, np.repeat(live[:1], 8 - len(live))]).astype(np.int32)
    live_inv = np.zeros(P, np.int32)
    live_inv[live] = np.arange(len(live), dtype=np.int32)
    return table, live, live_rows, live_inv


def _populated_state(rng, dims=PD, live=None):
    P, TP, SP = dims.pool_pages, dims.tpage, dims.spage
    if live is None:
        _, live, _, _ = _table_and_rows()
    state = plane.init_state(dims.pooled())
    sub = np.zeros((P, TP, SP), bool)
    mut = np.zeros((P, TP, SP), bool)
    vid = np.zeros((P, TP), bool)
    svc = np.zeros((P, TP), bool)
    pub = np.zeros((P, TP), bool)
    for p in live:
        sub[p] = rng.random((TP, SP)) < 0.7
        mut[p] = rng.random((TP, SP)) < 0.1
        vid[p] = rng.random(TP) < 0.6
        svc[p] = (rng.random(TP) < 0.3) & vid[p]
        pub[p] = rng.random(TP) < 0.9
    return state._replace(
        meta=state.meta._replace(
            is_video=jnp.asarray(vid), published=jnp.asarray(pub),
            is_svc=jnp.asarray(svc)),
        ctrl=state.ctrl._replace(
            subscribed=jnp.asarray(sub), sub_muted=jnp.asarray(mut)),
    )


def _rand_inputs(rng, live, dims=PD):
    P, TP, K, SP = dims.pool_pages, dims.tpage, dims.pkts, dims.spage

    def pk(lo, hi):
        a = np.zeros((P, TP, K), np.int32)
        for p in live:
            a[p] = rng.integers(lo, hi, (TP, K))
        return a

    def pkb(prob):
        a = np.zeros((P, TP, K), bool)
        for p in live:
            a[p] = rng.random((TP, K)) < prob
        return a

    def sb(shape, lo, hi):
        a = np.zeros(shape, np.float32)
        for p in live:
            a[p] = rng.uniform(lo, hi, shape[1:])
        return a

    kw = dict(
        sn=pk(0, 65536), ts=pk(0, 1 << 30), layer=pk(0, 3),
        temporal=pk(0, 4), keyframe=pkb(0.2), layer_sync=pkb(0.3),
        begin_pic=pkb(0.4), end_frame=pkb(0.4), pid=pk(0, 100),
        tl0=pk(0, 100), keyidx=pk(0, 30), size=pk(40, 1200),
        frame_ms=pk(0, 20), audio_level=pk(0, 127),
        arrival_rtp=pk(0, 1 << 28),
        ts_jump=np.zeros((P, TP, K), np.int32), valid=pkb(0.8),
        estimate=sb((P, SP), 1e5, 5e6),
        estimate_valid=sb((P, SP), 0, 1) > 0.5,
        nacks=sb((P, SP), 0, 3),
        pub_rtt_ms=sb((P, TP), 0, 80),
        fb_delay_ms=sb((P, SP), 0, 30), fb_recv_bps=sb((P, SP), 1e5, 4e6),
        fb_valid=sb((P, SP), 0, 1) > 0.4,
        fb_enabled=sb((P, SP), 0, 1) > 0.2,
        sub_reset=np.zeros((P, SP), bool),
        pad_num=np.zeros((P, SP), np.int32),
        pad_track=np.full((P, SP), -1, np.int32),
        tick_ms=np.int32(10), roll_quality=np.int32(0),
    )
    return plane.TickInputs(**{k: jnp.asarray(v) for k, v in kw.items()})


def _trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            return False
    return True


# -- freeze-the-dead ---------------------------------------------------------

def test_free_pages_frozen_under_stock_tick():
    """The invariant the live-extent path rests on: a FREE page's state
    is bit-identical to the init template after any number of stock
    ticks (without the freeze, pacer tokens / BWE counters / tracker
    windows advance even under zero input)."""
    rng = np.random.default_rng(3)
    table, live, _, _ = _table_and_rows()
    state = _populated_state(rng)
    tpl = plane.init_state(PD.pooled())
    step = jax.jit(lambda s, i: paged.paged_plane_tick(s, i, table))
    for t in range(3):
        state, _ = step(state, _rand_inputs(rng, live))
    dead = np.setdiff1d(np.arange(PD.pool_pages), live)
    for got, want in zip(jax.tree.leaves(state), jax.tree.leaves(tpl)):
        got, want = np.asarray(got), np.asarray(want)
        assert np.array_equal(got[dead], want[dead])


# -- model-level fused-tick parity -------------------------------------------

def test_fused_tick_bit_parity_fallback_and_interpret():
    """paged_plane_tick_fused (live-extent: kernel decide + compact
    phases + scatter + representative dead fill) is bit-identical to the
    stock full-pool tick — state AND outputs, every pool row — in both
    the gathered CPU fallback and Pallas interpret mode."""
    rng = np.random.default_rng(7)
    table, live, live_rows, live_inv = _table_and_rows()
    state = _populated_state(rng)
    stock = jax.jit(lambda s, i: paged.paged_plane_tick(s, i, table))
    fused_fb = jax.jit(lambda s, i: paged.paged_plane_tick_fused(
        s, i, table, live_rows, live_inv, use_pallas=False))
    fused_ik = jax.jit(lambda s, i: paged.paged_plane_tick_fused(
        s, i, table, live_rows, live_inv, use_pallas=False, interpret=True))
    s_stock = s_fb = s_ik = state
    for t in range(3):
        inp = _rand_inputs(rng, live)
        s_stock, o_stock = stock(s_stock, inp)
        s_fb, o_fb = fused_fb(s_fb, inp)
        s_ik, o_ik = fused_ik(s_ik, inp)
        assert _trees_equal(s_stock, s_fb) and _trees_equal(o_stock, o_fb), t
        assert _trees_equal(s_stock, s_ik) and _trees_equal(o_stock, o_ik), t


# -- page-local mix ----------------------------------------------------------

def test_mix_pages_matches_mix_tick():
    """Kernel mix (multiset kth-largest gate + weights matmul per page)
    equals ops/mix.mix_tick on the gathered live rows, including level
    TIES at the top-k boundary."""
    rng = np.random.default_rng(13)
    P, TP, SP, N = 16, 8, 4, 96
    live = np.array([1, 4, 5, 9, 10, 11, 12, 13], np.int32)
    pcm = rng.standard_normal((P, TP, N)).astype(np.float32) * 0.3
    level = rng.random((P, TP)).astype(np.float32)
    level[:, 2] = level[:, 5] = level[:, 7]     # exercise tie semantics
    active = rng.random((P, TP)) < 0.7
    sub_track = rng.integers(-1, TP, (P, SP)).astype(np.int32)
    gain = rng.uniform(0.5, 1.5, (P, TP)).astype(np.float32)
    ref = mix.mix_tick(jnp.asarray(pcm[live]), jnp.asarray(level[live]),
                       jnp.asarray(active[live]),
                       jnp.asarray(sub_track[live]), jnp.asarray(gain[live]))
    got = paged_kernel.mix_pages(pcm, level, active, sub_track, gain, live,
                                 interpret=True, use_pallas=False)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_decide_mix_single_pass():
    """decide_mix_pages: both output sets from ONE pallas_call — the
    mixed half must match the mix-only kernel bit-for-bit and the decide
    half must carry kernel routing (st/tr populated)."""
    rng = np.random.default_rng(17)
    P, TP, K, SP, N = 16, 8, 4, 8, 64
    live = np.array([2, 3, 7, 11], np.int32)
    pdims = plane.PlaneDims(P, TP, K, SP)
    st = plane.init_state(pdims)
    z = lambda sh, dt=np.int32: jnp.zeros(sh, dt)
    inp = plane.TickInputs(
        sn=z((P, TP, K)), ts=z((P, TP, K)), layer=z((P, TP, K)),
        temporal=z((P, TP, K)), keyframe=z((P, TP, K), bool),
        layer_sync=z((P, TP, K), bool), begin_pic=z((P, TP, K), bool),
        end_frame=z((P, TP, K), bool), pid=z((P, TP, K)),
        tl0=z((P, TP, K)), keyidx=z((P, TP, K)), size=z((P, TP, K)),
        frame_ms=z((P, TP, K)), audio_level=z((P, TP, K)),
        arrival_rtp=z((P, TP, K)), ts_jump=z((P, TP, K)),
        valid=z((P, TP, K), bool),
        estimate=z((P, SP), np.float32), estimate_valid=z((P, SP), bool),
        nacks=z((P, SP), np.float32), pub_rtt_ms=z((P, TP), np.float32),
        fb_delay_ms=z((P, SP), np.float32),
        fb_recv_bps=z((P, SP), np.float32), fb_valid=z((P, SP), bool),
        fb_enabled=z((P, SP), bool), sub_reset=z((P, SP), bool),
        pad_num=z((P, SP)), pad_track=jnp.full((P, SP), -1, jnp.int32),
        tick_ms=jnp.asarray(10, jnp.int32),
        roll_quality=jnp.asarray(0, jnp.int32),
    )
    base = st.ctrl.subscribed & ~st.ctrl.sub_muted & (
        st.meta.published & ~st.meta.pub_muted)[:, :, None]
    pcm = rng.standard_normal((P, TP, N)).astype(np.float32) * 0.3
    level = rng.random((P, TP)).astype(np.float32)
    active = rng.random((P, TP)) < 0.7
    sub_track = rng.integers(-1, TP, (P, SP)).astype(np.int32)
    gain = rng.uniform(0.5, 1.5, (P, TP)).astype(np.float32)
    only_mix = paged_kernel.mix_pages(
        pcm, level, active, sub_track, gain, live,
        interpret=True, use_pallas=False)
    dec, mixed = paged_kernel.decide_mix_pages(
        st.sel, st.meta.is_svc, st.meta.is_video, base, inp,
        pcm, level, active, sub_track, gain, live,
        wire_overhead=42, interpret=True, use_pallas=False)
    assert np.array_equal(np.asarray(only_mix), np.asarray(mixed))
    assert dec.st is not None and dec.tr is not None
    assert dec.send_bits.shape == (4, TP, K, 1)


# -- runtime acceptance gate -------------------------------------------------

ROOMS = [("a", 1, 2), ("b", 4, 8), ("c", 2, 5)]


def _setup_rooms(rt):
    handles = {}
    for name, tr, sb in ROOMS:
        s = rt.slots.alloc_room(name)
        handles[name] = s
        for i in range(tr):
            s.alloc_track(f"t{i}")
        for i in range(sb):
            s.alloc_sub(f"p{i}")
    rt.set_track(0, 0, published=True, is_video=True)
    rt.set_subscription(0, 0, 1, subscribed=True)
    rt.set_track(1, 0, published=True, is_video=True)
    rt.set_track(1, 3, published=True, is_video=False)
    for sub in range(8):
        rt.set_subscription(1, 0, sub, subscribed=True)
    rt.set_subscription(1, 3, 2, subscribed=True)
    rt.set_track(2, 1, published=True, is_video=False)
    rt.set_subscription(2, 1, 4, subscribed=True)
    return handles


def _push(rt, tick):
    for room, track, base in [(0, 0, 100), (1, 0, 500), (1, 3, 900),
                              (2, 1, 1300)]:
        for j in range(2):
            sn = base + tick * 2 + j
            rt.ingest.push(PacketIn(
                room=room, track=track, sn=sn & 0xFFFF,
                ts=(960 * (tick * 2 + j)) & 0xFFFFFFFF,
                size=120, payload=b"x" * 120,
                keyframe=(tick == 0 and j == 0),
                audio_level=-(30 + (sn % 20)),
            ))


def _capture(rt, log):
    orig = rt._unpack_outputs

    def wrapped(buf):
        out = orig(buf)
        log.append(out)
        return out

    rt._unpack_outputs = wrapped


async def test_runtime_parity_interpret_vs_stock():
    """The acceptance gate: paged_kernel="interpret" (live-extent tick,
    Pallas kernels in interpret mode) against paged_kernel="off" (stock
    jit pooled tick) on the mixed-size fixture — identical logical
    TickOutputs every tick AND identical post-run state, through a
    grow-on-join across a page boundary at tick 3 and a compaction move
    at tick 5."""
    off = PagedPlaneRuntime(PD, tick_ms=10, paged_kernel="off")
    ik = PagedPlaneRuntime(PD, tick_ms=10, paged_kernel="interpret")
    lo, li = [], []
    _capture(off, lo)
    _capture(ik, li)
    h_off = _setup_rooms(off)
    h_ik = _setup_rooms(ik)
    for t in range(8):
        for rt in (off, ik):
            _push(rt, t)
            await rt.step_once()
        assert _trees_equal(lo[-1], li[-1]), t
        if t == 3:      # grow room "a" across its spage=4 boundary
            for rt, hs in ((off, h_off), (ik, h_ik)):
                for i in range(2, 6):
                    hs["a"].alloc_sub(f"p{i}")
                rt.set_subscription(0, 0, 5, subscribed=True)
        if t == 5:      # free room "c", compact: pages of "b" relocate
            for rt in (off, ik):
                rt.slots.release_room("c")
                rt.compact()
    assert off.encode_snapshot(off.snapshot()) == \
        ik.encode_snapshot(ik.snapshot())
    assert ik.stats["paged_kernel_ticks"] == 8
    assert ik.stats["paged_kernel_steps"] > 0
    assert ik.recent_ticks[-1]["paged_kernel_ms"] >= 0.0
    assert 0.0 < ik.recent_ticks[-1]["page_live_fraction"] < 1.0
    assert off.stats["paged_kernel_ticks"] == 0


async def test_grid_steps_track_live_pages():
    """Scheduled work ∝ live pages: with one-page rooms, halving the
    room count halves the per-tick kernel grid steps at FIXED pool size
    — dead pages are never scheduled, not masked."""
    dims = paged.PagedDims(rooms=8, tracks=2, pkts=2, subs=4,
                           tpage=2, spage=4, pool_pages=8)

    async def run(n_rooms):
        rt = PagedPlaneRuntime(dims, tick_ms=10, paged_kernel="on")
        for r in range(n_rooms):
            s = rt.slots.alloc_room(f"r{r}")
            s.alloc_track("t0")
            s.alloc_sub("p0")
            rt.set_track(r, 0, published=True, is_video=False)
            rt.set_subscription(r, 0, 0, subscribed=True)
        for t in range(3):
            for r in range(n_rooms):
                rt.ingest.push(PacketIn(room=r, track=0, sn=100 + t,
                                        ts=960 * t, size=50, payload=b"a"))
            await rt.step_once()
        return rt.stats["paged_kernel_steps"], rt.stats["paged_kernel_ticks"]

    steps4, ticks4 = await run(4)
    steps2, ticks2 = await run(2)
    assert ticks4 == ticks2 == 3
    assert steps4 == 2 * steps2 > 0


async def test_zero_live_pages_tick():
    """NL == 0: no grid to schedule — the tick returns the representative
    dead page's outputs broadcast pool-wide, leaves state untouched, and
    records zero kernel steps."""
    rt = PagedPlaneRuntime(PD, tick_ms=10, paged_kernel="interpret")
    res = await rt.step_once()
    assert res.fwd_packets == 0
    assert rt.stats["paged_kernel_steps"] == 0
    assert rt.stats["paged_kernel_ticks"] == 1
    assert rt.pager_stats()["page_live_fraction"] == 0.0


# -- config knob -------------------------------------------------------------

def test_paged_kernel_config_validation():
    cfg = load_config(yaml_text="""
development: true
plane:
  pager_enabled: true
  paged_kernel: interpret
""")
    assert cfg.plane.paged_kernel == "interpret"
    with pytest.raises(ConfigError, match="paged_kernel"):
        load_config(yaml_text="development: true\nplane:\n"
                              "  pager_enabled: true\n"
                              "  paged_kernel: fast")
    # inert while the pager is off
    cfg = load_config(yaml_text="development: true\nplane:\n"
                                "  paged_kernel: fast")
    assert not cfg.plane.pager_enabled

    with pytest.raises(ValueError, match="paged_kernel"):
        PagedPlaneRuntime(PD, tick_ms=10, paged_kernel="bogus")
