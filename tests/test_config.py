"""Config system tests (reference: pkg/config/config.go defaults +
strict unmarshal, cmd/server/main.go flag/env merging)."""

import argparse

import pytest

from livekit_server_tpu.config import Config, ConfigError, generate_cli_flags, load_config


def test_defaults_dev_mode():
    cfg = load_config(yaml_text="development: true")
    assert cfg.port == 7880
    assert cfg.keys == {"devkey": "secret"}  # dev auto-keys (main.go:208)
    assert cfg.plane.tick_ms == 10
    assert cfg.rtc.congestion_control.enabled is True


def test_keys_required_outside_dev():
    with pytest.raises(ConfigError, match="API keys"):
        load_config(yaml_text="port: 7880")


def test_yaml_nested_merge_and_strictness():
    cfg = load_config(
        yaml_text="""
development: true
port: 9000
rtc:
  udp_port: 8882
  congestion_control:
    nack_ratio_threshold: 0.2
plane:
  rooms: 128
node_selector:
  kind: regionaware
  regions:
    - name: us-west
      lat: 37.6
      lon: -122.4
"""
    )
    assert cfg.port == 9000
    assert cfg.rtc.udp_port == 8882
    assert cfg.rtc.congestion_control.nack_ratio_threshold == 0.2
    assert cfg.plane.rooms == 128
    assert cfg.node_selector.regions[0].name == "us-west"
    # strict unknown-key rejection (main.go:197-200)
    with pytest.raises(ConfigError, match="unknown config key: bogus"):
        load_config(yaml_text="development: true\nbogus: 1")
    with pytest.raises(ConfigError, match="rtc.nope"):
        load_config(yaml_text="development: true\nrtc:\n  nope: 1")


def test_env_overrides_yaml():
    cfg = load_config(
        yaml_text="development: true\nport: 9000",
        env={"LIVEKIT_PORT": "9100", "LIVEKIT_PLANE_TICK_MS": "5"},
    )
    assert cfg.port == 9100
    assert cfg.plane.tick_ms == 5


def test_cli_overrides_env():
    parser = argparse.ArgumentParser()
    generate_cli_flags(parser)
    args = parser.parse_args(["--port", "9999", "--plane.rooms", "256", "--keys", "k:s"])
    cfg = load_config(yaml_text=None, cli_args=args, env={"LIVEKIT_PORT": "9100"})
    assert cfg.port == 9999
    assert cfg.plane.rooms == 256
    assert cfg.keys == {"k": "s"}


def test_invalid_plane_sizes():
    with pytest.raises(ConfigError, match="plane.tick_ms"):
        load_config(yaml_text="development: true\nplane:\n  tick_ms: 0")
