"""graftcheck: analyzer unit tests on fixtures + the real-tree gate.

Each rule is exercised on minimal good/bad fixture modules written to a
temp dir; the final tests run the full suite over the actual repo tree
(zero non-baselined findings — this is the tier-1 wiring) and unit-test
the retry_async extensions the GC04 migration leans on.
"""

from __future__ import annotations

import asyncio
import textwrap
from pathlib import Path

import pytest

from livekit_server_tpu.analysis import (
    core,
    gc01,
    gc02,
    gc03,
    gc04,
    gc05,
    gc06,
    gc07,
    gc08,
    gc09,
    diff_baseline,
    load_project,
    run_all,
    write_baseline,
)
from livekit_server_tpu.utils.backoff import (
    BackoffPolicy,
    CircuitBreaker,
    RetryAborted,
    retry_async,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


def make_project(tmp_path, files: dict[str, str]):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return load_project(tmp_path, ["pkg"])


def cfg_for(rule: str, **overrides) -> dict:
    merged = dict(core.DEFAULT_CONFIG[rule])
    merged["paths"] = ["pkg"]
    merged.update(overrides)
    return merged


def lines_of(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


# -- GC01 donation safety ---------------------------------------------------

GC01_FIXTURE = """\
    import asyncio

    class PlaneRuntime:
        def __init__(self):
            self.state = object()
            self.state_lock = asyncio.Lock()

        async def good(self):
            async with self.state_lock:
                self.state = self.state

        async def good_region(self):
            await self.state_lock.acquire()
            try:
                self.state = self.state
            finally:
                self.state_lock.release()

        async def bad(self):
            self.state = None            # line 20: lockless donated write

        async def bad_after_release(self):
            await self.state_lock.acquire()
            self.state_lock.release()
            x = self.state               # line 25: read after release

    class Manager:
        def __init__(self, runtime):
            self.runtime = runtime

        async def good(self):
            async with self.runtime.state_lock:
                return self.runtime.snapshot()

        async def bad(self):
            return self.runtime.snapshot()   # line 36: lockless state method
"""


def test_gc01_fixture(tmp_path):
    project = make_project(tmp_path, {"pkg/rt.py": GC01_FIXTURE})
    cfg = cfg_for("gc01", lock_held=["PlaneRuntime.__init__"])
    findings = gc01.run(project, cfg)
    assert all(f.rule == "GC01" for f in findings)
    assert lines_of(findings, "GC01") == [20, 25, 36]


def test_gc01_lock_held_allowlist(tmp_path):
    project = make_project(tmp_path, {"pkg/rt.py": GC01_FIXTURE})
    cfg = cfg_for(
        "gc01",
        lock_held=["PlaneRuntime.__init__", "PlaneRuntime.bad*",
                   "Manager.bad"],
    )
    assert gc01.run(project, cfg) == []


# Three-stage split: the lock-free staging half (ingest drain + probe
# mirrors, no donated-state access) must NOT be flagged, while the
# delta-upload/_replace half keeps the lock-held contract.
GC01_SPLIT_FIXTURE = """\
    import asyncio

    class PlaneRuntime:
        def __init__(self):
            self.state = object()
            self.state_lock = asyncio.Lock()
            self.ingest = object()
            self._dirty_rows = set()

        def _stage_host(self):
            inp = self.ingest            # host mirrors only: never flagged
            self._dirty_rows.add(1)
            return inp

        def _upload_ctrl(self):
            self.state = self.state      # allowed: caller-holds-lock contract

        async def good_tick(self):
            staged = self._stage_host()          # lock-free staging: OK
            async with self.state_lock:
                self._upload_ctrl()              # upload under the lock: OK
            return staged

        async def bad_tick(self):
            staged = self._stage_host()          # still fine lock-free
            self._upload_ctrl()                  # line 26: lockless upload
            self.state = None                    # line 27: lockless _replace
            return staged
"""


def test_gc01_three_stage_split(tmp_path):
    """Default config (the real tree's contract): _upload_ctrl/_device_step
    are state methods needing the lock; _stage_host is not."""
    project = make_project(tmp_path, {"pkg/rt.py": GC01_SPLIT_FIXTURE})
    findings = gc01.run(project, cfg_for("gc01"))
    assert all(f.rule == "GC01" for f in findings)
    assert lines_of(findings, "GC01") == [26, 27]


def test_gc01_staging_half_never_needs_lock(tmp_path):
    """Treating the drain/probe half as a state method would be a false
    positive factory — the default config must not include it."""
    assert "_stage_host" not in core.DEFAULT_CONFIG["gc01"]["state_methods"]
    assert "_schedule_probe" not in core.DEFAULT_CONFIG["gc01"]["state_methods"]
    good_only = GC01_SPLIT_FIXTURE.split("async def bad_tick")[0]
    project = make_project(tmp_path, {"pkg/rt.py": good_only})
    assert gc01.run(project, cfg_for("gc01")) == []


# -- GC02 tracer purity -----------------------------------------------------

GC02_FIXTURE = """\
    import time
    import jax
    import numpy as np

    def helper(x):
        return time.time() + x       # line 6: reachable from tick

    def host_side():
        return np.asarray(time.time())   # host: NOT reachable, no finding

    def build():
        def tick(state):
            t = time.time()          # line 13
            a = np.asarray(state)    # line 14
            return helper(t) + a
        return jax.jit(tick, donate_argnums=(0,))
"""


def test_gc02_nested_jit_closure(tmp_path):
    project = make_project(tmp_path, {"pkg/ops.py": GC02_FIXTURE})
    findings = gc02.run(project, cfg_for("gc02"))
    assert lines_of(findings, "GC02") == [6, 13, 14]


def test_gc02_rebound_shard_map_and_decorator(tmp_path):
    src = """\
        import functools
        import jax
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.partial(jax.jit, static_argnames=("k",))
        def mix(x, k):
            print(x)                 # line 7
            return x

        def make(mesh):
            def tick(state):
                import time
                return state, time.perf_counter()   # line 13
            smapped = _shard_map(tick, mesh=mesh)
            return jax.jit(smapped, donate_argnums=(0,))
    """
    project = make_project(tmp_path, {"pkg/mesh.py": src})
    findings = gc02.run(project, cfg_for("gc02"))
    assert lines_of(findings, "GC02") == [7, 13]


def test_gc02_pallas_call_with_prefetch_table(tmp_path):
    """The ragged paged-tick idiom: a kernel body handed to pl.pallas_call
    whose grid spec scalar-prefetches a page table. The body and the
    index-map lambdas both trace — host impurities inside either must
    flag; the builder around them is host code and must not."""
    src = """\
        import time
        import jax
        import numpy as np
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(live_ref, x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2
            t = time.perf_counter()      # line 9
            np.asarray(o_ref)            # line 10

        def build(live_rows, x):
            t0 = time.perf_counter()     # host: builder, no finding
            grid = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=(live_rows.shape[0],),
                in_specs=[pl.BlockSpec(
                    (1, 8), lambda i, lr: (lr[i], 0))],
                out_specs=pl.BlockSpec((1, 8), lambda i, lr: (i, 0)),
            )
            return pl.pallas_call(
                kernel, grid_spec=grid,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(live_rows, x)
    """
    project = make_project(tmp_path, {"pkg/pk.py": src})
    findings = gc02.run(project, cfg_for("gc02"))
    assert lines_of(findings, "GC02") == [9, 10]


# -- GC03 lock discipline ---------------------------------------------------

GC03_FIXTURE = """\
    import asyncio
    import time

    class M:
        def __init__(self):
            self.a_lock = asyncio.Lock()
            self.b_lock = asyncio.Lock()

        async def ab(self):
            async with self.a_lock:
                async with self.b_lock:   # edge a -> b
                    pass

        async def ba(self):
            async with self.b_lock:
                async with self.a_lock:   # line 16: closes the cycle
                    pass

        async def blocker(self):
            async with self.a_lock:
                time.sleep(1)             # line 21: blocks the loop

        async def reenter(self):
            async with self.a_lock:
                async with self.a_lock:   # line 25: not reentrant
                    pass
"""


def test_gc03_cycle_blocking_and_reentry(tmp_path):
    project = make_project(tmp_path, {"pkg/locks.py": GC03_FIXTURE})
    cfg = cfg_for("gc03", lock_names=["a_lock", "b_lock"])
    findings = gc03.run(project, cfg)
    msgs = [f.message for f in findings]
    assert any("lock-order cycle" in m for m in msgs)
    assert any("blocking call `time.sleep`" in m for m in msgs)
    assert any("re-acquisition of `a_lock`" in m for m in msgs)


def test_gc03_interprocedural_reacquire(tmp_path):
    src = """\
        import asyncio

        class M:
            def __init__(self):
                self.state_lock = asyncio.Lock()

            async def inner(self):
                async with self.state_lock:
                    pass

            async def outer(self):
                async with self.state_lock:
                    await self.inner()    # deadlock through the call
    """
    project = make_project(tmp_path, {"pkg/m.py": src})
    cfg = cfg_for("gc03", lock_names=["state_lock"])
    findings = gc03.run(project, cfg)
    assert any("call into `M.inner`" in f.message for f in findings)


# -- GC04 retry policy ------------------------------------------------------

GC04_BAD = """\
    import asyncio

    class C:
        async def reconnect(self):
            while True:                  # line 5: ad-hoc retry loop
                try:
                    r, w = await asyncio.open_connection("h", 1)  # line 7
                    return r, w
                except OSError:
                    await asyncio.sleep(0.1)
"""

GC04_GOOD = """\
    import asyncio
    from livekit_server_tpu.utils.backoff import retry_async

    class C:
        async def reconnect(self, policy):
            async def dial():
                return await asyncio.open_connection("h", 1)
            return await retry_async(dial, policy, retry_on=(OSError,))
"""


def test_gc04_bare_retry_loop(tmp_path):
    project = make_project(tmp_path, {"pkg/bus.py": GC04_BAD})
    findings = gc04.run(project, cfg_for("gc04"))
    assert lines_of(findings, "GC04") == [5, 7]


def test_gc04_retry_async_managed(tmp_path):
    project = make_project(tmp_path, {"pkg/bus.py": GC04_GOOD})
    assert gc04.run(project, cfg_for("gc04")) == []


# Periodic poll worker: the sleep is the SCHEDULE (top of body), not a
# reaction to failure — the service-plane workers' shape. Must not fire.
GC04_POLL = """\
    import asyncio

    class W:
        async def worker(self):
            while True:
                await asyncio.sleep(5.0)
                try:
                    await self.scan()
                except (ConnectionError, OSError):
                    continue
"""

# Tail-sleep retry: the handler swallows the net error and the loop
# sleeps AFTER the try — backoff in disguise. Must still fire.
GC04_TAIL = """\
    import asyncio

    class W:
        async def reconnect(self):
            while True:
                try:
                    await self.dial()
                    return
                except OSError:
                    pass
                await asyncio.sleep(0.1)
"""


def test_gc04_periodic_poll_is_not_a_retry_loop(tmp_path):
    project = make_project(tmp_path, {"pkg/worker.py": GC04_POLL})
    assert gc04.run(project, cfg_for("gc04")) == []


def test_gc04_tail_sleep_retry_still_fires(tmp_path):
    project = make_project(tmp_path, {"pkg/worker.py": GC04_TAIL})
    assert lines_of(gc04.run(project, cfg_for("gc04")), "GC04") == [5]


def test_gc04_scope_covers_service_plane():
    """The migration PR widened GC04 to the service plane: every bus
    send in service/ (handoffs, drains, admin RPC) must ride retry_async
    or tolerate-until-next-interval — never an ad-hoc backoff loop."""
    assert "livekit_server_tpu/service" in core.DEFAULT_CONFIG["gc04"]["paths"]


# -- GC05 bounded queues ----------------------------------------------------

GC05_FIXTURE = """\
    import asyncio
    from collections import deque

    class Buffers:
        def __init__(self):
            self.a = asyncio.Queue()              # line 6: no bound
            self.b = asyncio.Queue(maxsize=0)     # line 7: literal unbounded
            self.c = deque()                      # line 8: no bound
            self.d = deque(maxlen=None)           # line 9: literal unbounded
            self.e = asyncio.Queue(maxsize=8)     # bounded: OK
            self.f = asyncio.Queue(8)             # positional bound: OK
            self.g = deque(maxlen=16)             # bounded: OK
            self.h = deque([], 16)                # positional bound: OK
"""


def test_gc05_fixture(tmp_path):
    project = make_project(tmp_path, {"pkg/buf.py": GC05_FIXTURE})
    findings = gc05.run(project, cfg_for("gc05"))
    assert all(f.rule == "GC05" for f in findings)
    assert lines_of(findings, "GC05") == [6, 7, 8, 9]


def test_gc05_distinguishes_missing_from_zero(tmp_path):
    project = make_project(tmp_path, {"pkg/buf.py": GC05_FIXTURE})
    by_line = {f.line: f.message for f in gc05.run(project, cfg_for("gc05"))}
    assert "no maxsize= given" in by_line[6]
    assert "literally unbounded" in by_line[7]
    assert "no maxlen= given" in by_line[8]
    assert "literally unbounded" in by_line[9]


def test_gc05_inline_disable(tmp_path):
    suppressed = GC05_FIXTURE.replace(
        "# line 6: no bound", "# graftcheck: disable=GC05"
    ).replace(
        "# line 7: literal unbounded", "# graftcheck: disable=GC05"
    ).replace(
        "# line 8: no bound", "# graftcheck: disable=GC05"
    ).replace(
        "# line 9: literal unbounded", "# graftcheck: disable=GC05"
    )
    project = make_project(tmp_path, {"pkg/buf.py": suppressed})
    assert lines_of(run_all_pkg(project), "GC05") == []


def test_gc05_kwargs_splat_not_flagged(tmp_path):
    src = """\
        import asyncio

        def make(**kw):
            return asyncio.Queue(**kw)   # bound unknowable statically
    """
    project = make_project(tmp_path, {"pkg/buf.py": src})
    assert gc05.run(project, cfg_for("gc05")) == []


# -- GC06 checkpoint hygiene ------------------------------------------------

GC06_FIXTURE = """\
    import io
    import pickle
    import numpy as np

    from livekit_server_tpu.utils import checksum

    RAW = pickle.dumps({"boot": 1})        # line 7: module-level

    def encode(snap):
        buf = io.BytesIO()
        np.savez_compressed(buf, *snap)    # framed below: OK
        return checksum.encode_frame(buf.getvalue())

    def leak(snap):
        buf = io.BytesIO()
        np.savez_compressed(buf, *snap)    # line 16: no codec
        return buf.getvalue()

    def handoff(state):
        return state.tobytes()             # line 20: no codec
"""


def test_gc06_fixture(tmp_path):
    project = make_project(tmp_path, {"pkg/ckpt.py": GC06_FIXTURE})
    findings = gc06.run(project, cfg_for("gc06"))
    assert all(f.rule == "GC06" for f in findings)
    assert lines_of(findings, "GC06") == [7, 16, 20]


def test_gc06_module_level_vs_function(tmp_path):
    project = make_project(tmp_path, {"pkg/ckpt.py": GC06_FIXTURE})
    by_line = {f.line: f.message for f in gc06.run(project, cfg_for("gc06"))}
    assert "module-level" in by_line[7]
    assert "leak()" in by_line[16]
    assert "handoff()" in by_line[20]


def test_gc06_exempt_path(tmp_path):
    project = make_project(tmp_path, {"pkg/ckpt.py": GC06_FIXTURE})
    cfg = cfg_for("gc06", exempt=["pkg/ckpt.py"])
    assert gc06.run(project, cfg) == []


def test_gc06_inline_disable(tmp_path):
    suppressed = GC06_FIXTURE.replace(
        "# line 7: module-level", "# graftcheck: disable=GC06"
    ).replace(
        "# line 16: no codec", "# graftcheck: disable=GC06"
    ).replace(
        "# line 20: no codec", "# graftcheck: disable=GC06"
    )
    project = make_project(tmp_path, {"pkg/ckpt.py": suppressed})
    assert lines_of(run_all_pkg(project), "GC06") == []


def test_gc06_method_dumps_not_flagged(tmp_path):
    # A data-class `.dumps()` method is not pickle: the receiver must be
    # module-ish (pickle/cPickle/marshal) for the dumps/dump heuristic.
    src = """\
        def publish(self, codec, row):
            return self.codec.dumps(row)
    """
    project = make_project(tmp_path, {"pkg/pub.py": src})
    assert gc06.run(project, cfg_for("gc06")) == []


# -- GC07 emit hygiene ------------------------------------------------------

GC07_FIXTURE = """\
    class Recorder:
        def tick(self, bb, trace, idx, sn):
            bb.emit(3, 7, float(idx), 0.0)
            bb.emit(3, 7, f"room-{idx}")
            trace.record_tick(idx, {"late": 1})
            trace.set_shard(0, 0, [m for m in (1,)])
            bb.emit(3, 7, "r{}".format(idx))
            self.log.warn(f"room {idx} slow")
"""


def test_gc07_fixture(tmp_path):
    project = make_project(tmp_path, {"pkg/rec.py": GC07_FIXTURE})
    findings = gc07.run(project, cfg_for("gc07"))
    assert all(f.rule == "GC07" for f in findings)
    # the log.warn f-string is untouched: warn is not an emit call
    assert lines_of(findings, "GC07") == [4, 5, 6, 7]


def test_gc07_names_the_construct(tmp_path):
    project = make_project(tmp_path, {"pkg/rec.py": GC07_FIXTURE})
    by_line = {f.line: f.message for f in gc07.run(project, cfg_for("gc07"))}
    assert "f-string" in by_line[4]
    assert "dict display" in by_line[5]
    assert "comprehension" in by_line[6]
    assert "str.format" in by_line[7]


GC07_SAMPLED = """\
    class Recorder:
        def tick(self, bb, ws, idx, sn):
            if sn % 64 == 0:
                bb.emit(3, 7, f"room-{idx}")
            if self.sampled(sn):
                ws.observe_batch(sn, {"t": 0.0})
            mask = sn > 0
            if mask:
                bb.emit(3, 7, f"mask-{idx}")
            if idx > 3:
                bb.emit(3, 7, f"hot-{idx}")
"""


def test_gc07_sampling_branch_exempts(tmp_path):
    # modulo decimation, a *sample* name, and a *mask* name all exempt;
    # an arbitrary non-sampling condition does not.
    project = make_project(tmp_path, {"pkg/rec.py": GC07_SAMPLED})
    assert lines_of(gc07.run(project, cfg_for("gc07")), "GC07") == [11]


def test_gc07_str_mod_format_is_not_a_guard(tmp_path):
    # "x-%d" % idx allocates in the args; the Mod there must not read as
    # a decimation test on some enclosing if.
    src = """\
        def f(bb, idx):
            if idx > 3:
                bb.emit(3, 7, "x-%d" % idx)
    """
    project = make_project(tmp_path, {"pkg/rec.py": src})
    findings = gc07.run(project, cfg_for("gc07"))
    assert lines_of(findings, "GC07") == [3]
    assert "%-format" in findings[0].message


def test_gc07_inline_disable(tmp_path):
    suppressed = GC07_FIXTURE.replace(
        'bb.emit(3, 7, f"room-{idx}")',
        'bb.emit(3, 7, f"room-{idx}")  # graftcheck: disable=GC07',
    ).replace(
        'trace.record_tick(idx, {"late": 1})',
        'trace.record_tick(idx, {"late": 1})  # graftcheck: disable=GC07',
    ).replace(
        "trace.set_shard(0, 0, [m for m in (1,)])",
        "trace.set_shard(0, 0, [m for m in (1,)])"
        "  # graftcheck: disable=GC07",
    ).replace(
        'bb.emit(3, 7, "r{}".format(idx))',
        'bb.emit(3, 7, "r{}".format(idx))  # graftcheck: disable=GC07',
    )
    project = make_project(tmp_path, {"pkg/rec.py": suppressed})
    assert lines_of(run_all_pkg(project), "GC07") == []


def test_gc07_emit_calls_configurable(tmp_path):
    project = make_project(tmp_path, {"pkg/rec.py": GC07_FIXTURE})
    cfg = cfg_for("gc07", emit_calls=["record_tick"])
    assert lines_of(gc07.run(project, cfg), "GC07") == [5]


# -- GC08 page-handle discipline --------------------------------------------

GC08_BAD = """\
    class Mover:
        async def relocate(self, rt, row):
            pages = rt.pager.pages_of_room(row)       # line 3: mint
            await rt.bus.publish("moving", row)       # line 4: boundary
            return rt.state_rows(pages)               # line 5: stale use
"""

GC08_LOCK_BAD = """\
    class Mover:
        async def relocate(self, rt, row):
            async with rt.state_lock:
                pages = rt.pager.pages_of_room(row)
            return rt.state_rows(pages)               # line 5: after release
"""

GC08_GOOD = """\
    class Mover:
        async def relocate(self, rt, row):
            pages = rt.pager.pages_of_room(row)
            self.touch(pages)                         # same epoch: fine
            await rt.bus.publish("moving", row)
            rt.pager.check_epoch(self.epoch)          # revalidated
            return rt.state_rows(pages)

        async def refetch(self, rt, row):
            pages = rt.pager.pages_of_room(row)
            await rt.bus.publish("moving", row)
            pages = rt.pager.pages_of_room(row)       # re-mint: fine
            return rt.state_rows(pages)
"""


def test_gc08_await_boundary(tmp_path):
    project = make_project(tmp_path, {"pkg/mover.py": GC08_BAD})
    findings = gc08.run(project, cfg_for("gc08"))
    assert lines_of(findings, "GC08") == [5]
    assert "an await" in findings[0].message
    assert "check_epoch" in findings[0].hint


def test_gc08_lock_release_boundary(tmp_path):
    project = make_project(tmp_path, {"pkg/mover.py": GC08_LOCK_BAD})
    findings = gc08.run(project, cfg_for("gc08"))
    assert lines_of(findings, "GC08") == [5]
    assert "state_lock" in findings[0].message


def test_gc08_revalidate_and_remint_exempt(tmp_path):
    project = make_project(tmp_path, {"pkg/mover.py": GC08_GOOD})
    assert gc08.run(project, cfg_for("gc08")) == []


def test_gc08_inline_disable(tmp_path):
    suppressed = GC08_BAD.replace(
        'return rt.state_rows(pages)               # line 5: stale use',
        'return rt.state_rows(pages)  # graftcheck: disable=GC08',
    )
    project = make_project(tmp_path, {"pkg/mover.py": suppressed})
    assert lines_of(run_all_pkg(project), "GC08") == []


def test_gc08_use_before_boundary_is_fine(tmp_path):
    src = """\
        class Mover:
            async def relocate(self, rt, row):
                pages = rt.pager.pages_of_room(row)
                out = rt.state_rows(pages)
                await rt.bus.publish("done", row)
                return out
    """
    project = make_project(tmp_path, {"pkg/mover.py": src})
    assert gc08.run(project, cfg_for("gc08")) == []


# -- GC09 fencing discipline ------------------------------------------------

GC09_BAD = """\
    class Manager:
        async def checkpoint(self, name, payload):
            key = f"room_checkpoint:{name}:gen"
            await self.bus.set(key, payload, 30.0)
            await self.bus.set(
                f"room_checkpoint:{name}:gen", payload, 30.0)
            await self.bus.delete("room_snapshot:a")

        async def pin(self, bus, name, node):
            await bus.hset(NODE_ROOM_KEY, name, node)
            await bus.hdel("room_node_map", name)
"""

GC09_GOOD = """\
    class Manager:
        async def checkpoint(self, name, payload):
            await self.fence.guarded_set(
                name, f"room_checkpoint:{name}:gen", payload)
            await self.bus.set(f"node_lease:{name}", "1", 6.0)
            await self.bus.hset("nodes", name, payload)

    class KVRouter:
        async def set_node_for_room(self, name, node):
            await self.bus.hset(NODE_ROOM_KEY, name, node)

    class RoomFence:
        async def release(self, room):
            await self.bus.delete(f"room_epoch:{room}")
"""


def test_gc09_unfenced_literal_writes(tmp_path):
    # line 4 (variable key) is the sanctioned indirection and passes;
    # lines 5/7 (literal fenced prefixes) and 10/11 (pin hash by module
    # constant and by literal) are findings.
    project = make_project(tmp_path, {"pkg/mgr.py": GC09_BAD})
    findings = gc09.run(project, cfg_for("gc09"))
    assert lines_of(findings, "GC09") == [5, 7, 10, 11]
    assert "epoch" in findings[0].hint


def test_gc09_writer_api_and_variable_keys_exempt(tmp_path):
    # guarded_set isn't a bus call, node_lease:/nodes aren't fenced
    # keys, and the fence/pin-mover bodies are allowlisted.
    project = make_project(tmp_path, {"pkg/mgr.py": GC09_GOOD})
    assert gc09.run(project, cfg_for("gc09")) == []


def test_gc09_allowlist_is_load_bearing(tmp_path):
    project = make_project(tmp_path, {"pkg/mgr.py": GC09_GOOD})
    findings = gc09.run(project, cfg_for("gc09", allowed_in=[]))
    assert [f.line for f in findings] == [10, 14]


def test_gc09_inline_disable(tmp_path):
    suppressed = GC09_BAD.replace(
        'await self.bus.delete("room_snapshot:a")',
        'await self.bus.delete("room_snapshot:a")'
        "  # graftcheck: disable=GC09",
    )
    project = make_project(tmp_path, {"pkg/mgr.py": suppressed})
    assert lines_of(run_all_pkg(project), "GC09") == [5, 10, 11]


# -- suppressions -----------------------------------------------------------

def run_all_pkg(project):
    config = core.Config(root=project.root, paths=["pkg"])
    config.rules = {r.lower(): {"paths": ["pkg"]} for r in core.RULES}
    return run_all(project, config)


def test_exact_line_disable(tmp_path):
    bad = GC04_BAD.replace(
        'await asyncio.open_connection("h", 1)  # line 7',
        'await asyncio.open_connection("h", 1)  # graftcheck: disable=GC04',
    ).replace(
        "while True:                  # line 5: ad-hoc retry loop",
        "while True:  # graftcheck: disable=GC04",
    )
    project = make_project(tmp_path, {"pkg/bus.py": bad})
    assert run_all_pkg(project) == []


def test_disable_is_rule_specific(tmp_path):
    bad = GC04_BAD.replace(
        'await asyncio.open_connection("h", 1)  # line 7',
        'await asyncio.open_connection("h", 1)  # graftcheck: disable=GC01',
    )
    project = make_project(tmp_path, {"pkg/bus.py": bad})
    # wrong rule id on the dial line: both GC04 findings survive
    assert lines_of(run_all_pkg(project), "GC04") == [5, 7]


def test_file_level_disable(tmp_path):
    bad = "# graftcheck: disable-file=GC04\n" + textwrap.dedent(GC04_BAD)
    project = make_project(tmp_path, {"pkg/bus.py": bad})
    assert run_all_pkg(project) == []


def test_parse_error_is_a_finding(tmp_path):
    project = make_project(tmp_path, {"pkg/broken.py": "def f(:\n"})
    findings = run_all_pkg(project)
    assert [f.rule for f in findings] == [core.PARSE_RULE]


# -- baseline ---------------------------------------------------------------

def test_baseline_roundtrip_and_stale(tmp_path):
    project = make_project(tmp_path, {"pkg/bus.py": GC04_BAD})
    findings = run_all_pkg(project)
    assert findings
    bl_path = tmp_path / "baseline.json"
    write_baseline(bl_path, findings, project)
    baseline = core.load_baseline(bl_path)

    # same tree: fully covered, nothing stale
    new, stale = diff_baseline(findings, baseline, project)
    assert new == [] and stale == []

    # one finding fixed: its entry is now stale — the run must fail so
    # the baseline only ever shrinks
    new, stale = diff_baseline(findings[1:], baseline, project)
    assert new == [] and len(stale) == 1

    # a brand-new finding is never absorbed by unrelated entries
    extra = core.Finding("GC01", "pkg/bus.py", 1, "x")
    new, _ = diff_baseline(findings + [extra], baseline, project)
    assert new == [extra]


# -- the real tree ----------------------------------------------------------

def test_real_tree_is_clean():
    """The tier-1 gate: all four analyzers over livekit_server_tpu/ with
    zero findings beyond the committed (shrink-only) baseline."""
    config = core.load_config(REPO_ROOT)
    project = load_project(REPO_ROOT, config.paths)
    findings = run_all(project, config)
    baseline = core.load_baseline(REPO_ROOT / config.baseline)
    new, stale = diff_baseline(findings, baseline, project)
    assert new == [], "\n".join(f.render() for f in new)
    assert stale == [], f"stale baseline entries (remove them): {stale}"


def test_runner_exits_zero_on_real_tree(capsys):
    from tools.check import main

    assert main(["--no-compile"]) == 0
    out = capsys.readouterr().out
    assert "clean" in out


# -- retry_async extensions (GC04's landing pad) ----------------------------

def test_retry_async_on_give_up():
    calls = []

    async def always_fails():
        raise ConnectionError("nope")

    async def run():
        with pytest.raises(ConnectionError):
            await retry_async(
                always_fails,
                BackoffPolicy(base=0.0, max_attempts=3, jitter=False),
                on_give_up=lambda n, e: calls.append((n, type(e).__name__)),
            )

    asyncio.run(run())
    assert calls == [(3, "ConnectionError")]


def test_retry_async_default_give_up_logs():
    import io

    from livekit_server_tpu.utils import logger as logger_mod

    buf = io.StringIO()
    logger_mod.configure(stream=buf)

    async def always_fails():
        raise ConnectionError("nope")

    async def run():
        with pytest.raises(ConnectionError):
            await retry_async(
                always_fails,
                BackoffPolicy(base=0.0, max_attempts=2, jitter=False),
            )

    try:
        asyncio.run(run())
        out = buf.getvalue()
        assert "retry_async giving up" in out and "attempts=2" in out
    finally:
        logger_mod.configure()


def test_retry_async_should_abort():
    attempts = []

    async def fails():
        attempts.append(1)
        raise OSError("down")

    async def run():
        with pytest.raises(RetryAborted):
            await retry_async(
                fails,
                BackoffPolicy(base=0.0, jitter=False),
                retry_on=(OSError,),
                should_abort=lambda: len(attempts) >= 2,
            )

    asyncio.run(run())
    assert len(attempts) == 2


def test_retry_async_wait_when_open():
    breaker = CircuitBreaker(threshold=1, cooldown_s=0.01)
    state = {"n": 0}

    async def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise ConnectionError("down")
        return "up"

    async def run():
        return await retry_async(
            flaky,
            BackoffPolicy(base=0.0, jitter=False),
            breaker=breaker,
            wait_when_open=True,
        )

    assert asyncio.run(run()) == "up"
    assert breaker.trips >= 1
