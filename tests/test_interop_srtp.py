"""SRTP AEAD_AES_128_GCM against RFC 7714/3711 test vectors + properties."""

from livekit_server_tpu.interop import srtp


def _vector_session() -> srtp.SrtpSession:
    """Session with the RFC 7714 §16.1 SESSION key/salt installed directly
    (the RFC vectors give derived keys, not masters)."""
    s = srtp.SrtpSession(master_key=bytes(16), master_salt=bytes(12))
    s.rtp_key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    s.rtp_salt = bytes.fromhex("517569642070726f2071756f")
    s.rtcp_key = s.rtp_key
    s.rtcp_salt = s.rtp_salt
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    s._rtp_aead = AESGCM(s.rtp_key)
    s._rtcp_aead = AESGCM(s.rtcp_key)
    return s


RFC7714_RTP_CLEAR = bytes.fromhex(
    "8040f17b8041f8d35501a0b2"
) + b"Gallia est omnis divisa in partes tres"
RFC7714_RTP_PROTECTED = bytes.fromhex(
    "8040f17b8041f8d35501a0b2"
    "f24de3a3fb34de6cacba861c9d7e4bcabe633bd50d294e6f42a5f47a"
    "51c7d19b36de3adf8833899d7f27beb16a9152cf765ee4390cce"
)


def test_rfc3711_kdf_vectors():
    mk = bytes.fromhex("E1F97A0D3E018BE0D64FA32C06DE4139")
    ms = bytes.fromhex("0EC675AD498AFEEBB6960B3AABE6")
    assert srtp._aes_cm_derive(mk, ms, 0x00, 16).hex() == (
        "c61e7a93744f39ee10734afe3ff7a087"
    )
    assert srtp._aes_cm_derive(mk, ms, 0x02, 14).hex() == (
        "30cbbc08863d8c85d49db34a9ae1"
    )
    assert srtp._aes_cm_derive(mk, ms, 0x01, 20).hex() == (
        "cebe321f6ff7716b6fd4ab49af256a156d38baa4"
    )


def test_rfc7714_rtp_protect_vector():
    s = _vector_session()
    assert s.protect_rtp(RFC7714_RTP_CLEAR, roc=0) == RFC7714_RTP_PROTECTED


def test_rfc7714_rtp_unprotect_vector():
    s = _vector_session()
    assert s.unprotect_rtp(RFC7714_RTP_PROTECTED, roc=0) == RFC7714_RTP_CLEAR


def test_rtp_tamper_rejected():
    s = _vector_session()
    bad = bytearray(RFC7714_RTP_PROTECTED)
    bad[20] ^= 1
    assert s.unprotect_rtp(bytes(bad), roc=0) is None


def _rtp(seq: int, ssrc: int = 0x1234, payload: bytes = b"x" * 30) -> bytes:
    return (
        bytes([0x80, 96])
        + seq.to_bytes(2, "big")
        + (seq * 960).to_bytes(4, "big")
        + ssrc.to_bytes(4, "big")
        + payload
    )


def test_rtp_roundtrip_replay_and_roc():
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    # Sequence crossing the 16-bit wrap: ROC must advance on both sides.
    seqs = [0xFFFE, 0xFFFF, 0, 1, 2]
    wire = [tx.protect_rtp(_rtp(q)) for q in seqs]
    for q, w in zip(seqs, wire):
        out = rx.unprotect_rtp(w)
        assert out == _rtp(q), f"seq {q:#x}"
    assert rx._rx[0x1234][0] == 1  # ROC advanced past the wrap
    # Replay of an already-seen packet is rejected.
    assert rx.unprotect_rtp(wire[-1]) is None
    assert rx.unprotect_rtp(wire[0]) is None


def test_rtp_header_with_csrc_and_extension():
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    # CC=2 + one extension word: the AAD must cover the full header.
    hdr = bytearray(_rtp(7))
    hdr[0] = 0x80 | 0x10 | 2  # X + CC=2
    pkt = (
        bytes(hdr[:12])
        + b"\x00\x00\x00\x01\x00\x00\x00\x02"          # 2 CSRCs
        + b"\xbe\xde\x00\x01" + b"\x10\x40\x00\x00"    # one ext word
        + b"payload!"
    )
    out = rx.unprotect_rtp(tx.protect_rtp(pkt))
    assert out == pkt


def test_rtcp_roundtrip_and_tamper():
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rr = bytes([0x81, 201, 0, 7]) + (0xCAFE).to_bytes(4, "big") + bytes(24)
    w = tx.protect_rtcp(rr)
    assert rx.unprotect_rtcp(w) == rr
    bad = bytearray(w)
    bad[10] ^= 1
    assert rx.unprotect_rtcp(bytes(bad)) is None
    # E-bit clear (unencrypted SRTCP) is not accepted.
    noe = bytearray(w)
    noe[-4] &= 0x7F
    assert rx.unprotect_rtcp(bytes(noe)) is None
