"""SRTP AEAD_AES_128_GCM against RFC 7714/3711 test vectors + properties."""

import pytest

pytest.importorskip("cryptography")  # OpenSSL-backed interop lane; absent in slim images

from livekit_server_tpu.interop import srtp


def _vector_session() -> srtp.SrtpSession:
    """Session with the RFC 7714 §16.1 SESSION key/salt installed directly
    (the RFC vectors give derived keys, not masters)."""
    s = srtp.SrtpSession(master_key=bytes(16), master_salt=bytes(12))
    s.rtp_key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    s.rtp_salt = bytes.fromhex("517569642070726f2071756f")
    s.rtcp_key = s.rtp_key
    s.rtcp_salt = s.rtp_salt
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    s._rtp_aead = AESGCM(s.rtp_key)
    s._rtcp_aead = AESGCM(s.rtcp_key)
    return s


RFC7714_RTP_CLEAR = bytes.fromhex(
    "8040f17b8041f8d35501a0b2"
) + b"Gallia est omnis divisa in partes tres"
RFC7714_RTP_PROTECTED = bytes.fromhex(
    "8040f17b8041f8d35501a0b2"
    "f24de3a3fb34de6cacba861c9d7e4bcabe633bd50d294e6f42a5f47a"
    "51c7d19b36de3adf8833899d7f27beb16a9152cf765ee4390cce"
)


def test_rfc3711_kdf_vectors():
    mk = bytes.fromhex("E1F97A0D3E018BE0D64FA32C06DE4139")
    ms = bytes.fromhex("0EC675AD498AFEEBB6960B3AABE6")
    assert srtp._aes_cm_derive(mk, ms, 0x00, 16).hex() == (
        "c61e7a93744f39ee10734afe3ff7a087"
    )
    assert srtp._aes_cm_derive(mk, ms, 0x02, 14).hex() == (
        "30cbbc08863d8c85d49db34a9ae1"
    )
    assert srtp._aes_cm_derive(mk, ms, 0x01, 20).hex() == (
        "cebe321f6ff7716b6fd4ab49af256a156d38baa4"
    )


def test_rfc7714_rtp_protect_vector():
    s = _vector_session()
    assert s.protect_rtp(RFC7714_RTP_CLEAR, roc=0) == RFC7714_RTP_PROTECTED


def test_rfc7714_rtp_unprotect_vector():
    s = _vector_session()
    assert s.unprotect_rtp(RFC7714_RTP_PROTECTED, roc=0) == RFC7714_RTP_CLEAR


def test_rtp_tamper_rejected():
    s = _vector_session()
    bad = bytearray(RFC7714_RTP_PROTECTED)
    bad[20] ^= 1
    assert s.unprotect_rtp(bytes(bad), roc=0) is None


def _rtp(seq: int, ssrc: int = 0x1234, payload: bytes = b"x" * 30) -> bytes:
    return (
        bytes([0x80, 96])
        + seq.to_bytes(2, "big")
        + (seq * 960).to_bytes(4, "big")
        + ssrc.to_bytes(4, "big")
        + payload
    )


def test_rtp_roundtrip_replay_and_roc():
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    # Sequence crossing the 16-bit wrap: ROC must advance on both sides.
    seqs = [0xFFFE, 0xFFFF, 0, 1, 2]
    wire = [tx.protect_rtp(_rtp(q)) for q in seqs]
    for q, w in zip(seqs, wire):
        out = rx.unprotect_rtp(w)
        assert out == _rtp(q), f"seq {q:#x}"
    assert rx._rx[0x1234][0] == 1  # ROC advanced past the wrap
    # Replay of an already-seen packet is rejected.
    assert rx.unprotect_rtp(wire[-1]) is None
    assert rx.unprotect_rtp(wire[0]) is None


def test_rtp_header_with_csrc_and_extension():
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    # CC=2 + one extension word: the AAD must cover the full header.
    hdr = bytearray(_rtp(7))
    hdr[0] = 0x80 | 0x10 | 2  # X + CC=2
    pkt = (
        bytes(hdr[:12])
        + b"\x00\x00\x00\x01\x00\x00\x00\x02"          # 2 CSRCs
        + b"\xbe\xde\x00\x01" + b"\x10\x40\x00\x00"    # one ext word
        + b"payload!"
    )
    out = rx.unprotect_rtp(tx.protect_rtp(pkt))
    assert out == pkt


def test_rtcp_roundtrip_and_tamper():
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rr = bytes([0x81, 201, 0, 7]) + (0xCAFE).to_bytes(4, "big") + bytes(24)
    w = tx.protect_rtcp(rr)
    assert rx.unprotect_rtcp(w) == rr
    bad = bytearray(w)
    bad[10] ^= 1
    assert rx.unprotect_rtcp(bytes(bad)) is None
    # E-bit clear (unencrypted SRTCP) is not accepted.
    noe = bytearray(w)
    noe[-4] &= 0x7F
    assert rx.unprotect_rtcp(bytes(noe)) is None

def test_srtcp_replay_rejected():
    """RFC 3711 §3.3.2: a replayed (authenticated) SRTCP packet must not
    decrypt twice — an on-path attacker could otherwise re-feed old
    REMB/TWCC to skew BWE."""
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rr = bytes([0x81, 201, 0, 7]) + (0xCAFE).to_bytes(4, "big") + bytes(24)
    w1, w2, w3 = (tx.protect_rtcp(rr) for _ in range(3))
    assert rx.unprotect_rtcp(w1) == rr
    assert rx.unprotect_rtcp(w2) == rr
    assert rx.unprotect_rtcp(w1) is None        # replay
    assert rx.unprotect_rtcp(w2) is None        # replay
    assert rx.unprotect_rtcp(w3) == rr          # fresh index still fine
    # Out-of-order but unseen index inside the window is accepted once.
    w4, w5 = tx.protect_rtcp(rr), tx.protect_rtcp(rr)
    assert rx.unprotect_rtcp(w5) == rr
    assert rx.unprotect_rtcp(w4) == rr
    assert rx.unprotect_rtcp(w4) is None


def test_tx_roc_wrap_with_large_gap():
    """A >4096-packet SN gap crossing the 16-bit wrap must still bump the
    sender ROC (half-range rule), or the stream permanently desyncs from
    the receiver's RFC 3711 §3.3.1 estimator."""
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    # Last pre-wrap SN far below 0xF000, first post-wrap SN far above
    # 0x1000: the old threshold heuristic missed this entirely.
    for seq in [0xE000, 0x2000, 0x2001]:
        w = tx.protect_rtp(_rtp(seq))
        assert rx.unprotect_rtp(w) == _rtp(seq), f"seq {seq:#x}"
    assert tx._tx[0x1234][0] == 1
    assert rx._rx[0x1234][0] == 1


def test_tx_roc_cross_wrap_rtx_uses_previous_roc():
    """Retransmitting a pre-wrap SN right after the wrap must protect
    under roc-1 so the receiver's estimator (which guesses roc-1 for a
    backward step across the wrap) can decrypt it."""
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    for seq in [0xFFFE, 0xFFFF, 0, 1]:
        assert rx.unprotect_rtp(tx.protect_rtp(_rtp(seq))) == _rtp(seq)
    # RTX of 0xFFFE (sent under roc=0) while the stream is at roc=1.
    w = tx.protect_rtp(_rtp(0xFFFD, payload=b"y" * 30))
    assert rx.unprotect_rtp(w) == _rtp(0xFFFD, payload=b"y" * 30)
    assert tx._tx[0x1234][0] == 1  # stream ROC state undisturbed


def test_tx_roc_large_forward_jump_stays_in_lockstep_with_rx():
    """TX protects every packet under exactly the ROC the RFC 3711
    §3.3.1 estimator guesses — so even a >2^15 forward SN jump (which a
    standard receiver half-range-decodes as roc-1) decrypts, and the two
    sides' state stays identical packet by packet."""
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    for seq in [1000, 1001]:
        assert rx.unprotect_rtp(tx.protect_rtp(_rtp(seq))) == _rtp(seq)
    # +40000 jump: both sides resolve it as roc-1 (half-range rule) — the
    # receiver then correctly REJECTS it as far behind the replay window
    # (libsrtp does the same; senders must not jump >2^15) — and neither
    # side advances its highest-SN state, so they stay in lockstep.
    for seq in [41001, 41002, 41003]:
        assert rx.unprotect_rtp(tx.protect_rtp(_rtp(seq))) is None
    assert tx._tx[0x1234][:2] == [0, 1001]
    assert rx._rx[0x1234][:2] == [0, 1001]
    # Once the stream passes the pinned SN again, state resumes advancing.
    for seq in [1002, 1003]:
        assert rx.unprotect_rtp(tx.protect_rtp(_rtp(seq))) == _rtp(seq)
    assert tx._tx[0x1234][:2] == [0, 1003]


def test_tx_rx_lockstep_fuzz():
    """Property: for ANY SN pattern a sender emits, a fresh receiver that
    sees every packet decrypts every packet (the sender mirrors the
    receiver's estimator, so divergence is impossible without loss)."""
    import random

    rng = random.Random(7)
    tx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    rx = srtp.SrtpSession(master_key=b"k" * 16, master_salt=b"s" * 12)
    seq = 60000
    seen = set()
    for _ in range(400):
        r = rng.random()
        if r < 0.70:
            seq = (seq + 1) & 0xFFFF
        elif r < 0.85:
            seq = (seq + rng.randint(2, 3000)) & 0xFFFF  # loss burst
        else:
            seq = (seq - rng.randint(1, 40)) & 0xFFFF    # RTX reach-back
        if seq in seen:
            continue  # replay window would (correctly) reject a dup
        seen.add(seq)
        w = tx.protect_rtp(_rtp(seq))
        out = rx.unprotect_rtp(w)
        # The receiver may reject packets that fall behind its 64-wide
        # replay window — but must never fail to DECRYPT one it accepts,
        # and in-window packets must round-trip.
        assert out in (None, _rtp(seq))
        if out is None:
            cur = (rx._rx[0x1234][0] << 16) | rx._rx[0x1234][1]
            idx = (srtp._estimate_roc(
                rx._rx[0x1234][0], rx._rx[0x1234][1], seq) << 16) | seq
            assert cur - idx >= 64, "rejected a packet inside the window"
    assert tx._tx[0x1234][:2] == rx._rx[0x1234][:2]
