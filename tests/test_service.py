"""Service-layer integration tests over real HTTP + WebSocket.

Reference parity: test/ integration tier (integration_helpers.go
createSingleNodeServer → real server + real WS clients;
singlenode_test.go scenarios: connect, duplicate identity, publisher +
subscriber media, permissions) and roomservice_test.go (admin API).
The in-process server binds a real port; clients are aiohttp WS sessions
speaking the JSON signal protocol + msgpack media frames.
"""

import asyncio
import json

import aiohttp
import msgpack
import pytest

from livekit_server_tpu.auth import AccessToken, VideoGrant
from livekit_server_tpu.config import load_config
from livekit_server_tpu.service.server import create_server

API_KEY, API_SECRET = "testkey", "testsecret"


def make_config(port: int, require_encryption: bool = False, **plane_overrides):
    plane = {"rooms": 4, "tracks_per_room": 4, "pkts_per_track": 4, "subs_per_room": 4,
             "tick_ms": 10} | plane_overrides
    return load_config(
        yaml_text=json.dumps(
            {
                "keys": {API_KEY: API_SECRET},
                "port": port,
                "bind_addresses": ["127.0.0.1"],
                "plane": plane,
                "room": {"empty_timeout_s": 2},
                # Ports offset to avoid cross-test collisions. Most tests
                # keep the legacy cleartext wire; the encrypted-path test
                # opts in to the (production-default) sealed wire.
                "rtc": {
                    "udp_port": port + 1,
                    "tcp_port": port + 2,
                    "require_encryption": require_encryption,
                },
            }
        )
    )


def token(identity: str, room: str, **grant_kw) -> str:
    t = AccessToken(API_KEY, API_SECRET)
    t.identity = identity
    t.grant = VideoGrant(room_join=True, room=room, **grant_kw)
    return t.to_jwt()


def admin_token(room: str = "") -> str:
    """roomAdmin is room-scoped (auth.go EnsureAdminPermission): per-room
    ops need a token whose room claim names the target room."""
    t = AccessToken(API_KEY, API_SECRET)
    t.identity = "admin"
    t.grant = VideoGrant(room_admin=True, room_create=True, room_list=True, room=room)
    return t.to_jwt()


class SignalClient:
    """Minimal test client (test/client/client.go RTCClient analog)."""

    def __init__(self, session: aiohttp.ClientSession, port: int):
        self.session = session
        self.port = port
        self.ws = None
        self.signals: list = []
        self.media: list = []
        self._reader: asyncio.Task | None = None

    async def connect(self, room: str, identity: str, query: str = "", **grant_kw):
        self.ws = await self.session.ws_connect(
            f"ws://127.0.0.1:{self.port}/rtc?access_token="
            f"{token(identity, room, **grant_kw)}{query}"
        )
        self._reader = asyncio.ensure_future(self._read())
        join = await self.wait_for("join")
        return join

    async def _read(self):
        async for msg in self.ws:
            if msg.type == aiohttp.WSMsgType.TEXT:
                self.signals.append(json.loads(msg.data))
            elif msg.type == aiohttp.WSMsgType.BINARY:
                self.media.append(msgpack.unpackb(msg.data, raw=False))

    async def wait_for(self, kind: str, timeout: float = 3.0):
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            for m in self.signals:
                if kind in m:
                    return m[kind]
            await asyncio.sleep(0.01)
        raise TimeoutError(f"no {kind!r} in {self.signals}")

    async def wait_media(self, n: int = 1, timeout: float = 3.0):
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if len(self.media) >= n:
                return self.media
            await asyncio.sleep(0.01)
        raise TimeoutError(f"only {len(self.media)} media frames")

    async def send_signal(self, kind: str, data: dict):
        await self.ws.send_str(json.dumps({kind: data}))

    async def send_media(self, **frame):
        await self.ws.send_bytes(msgpack.packb(frame))

    async def close(self):
        if self._reader:
            self._reader.cancel()
        if self.ws is not None:
            await self.ws.close()


import contextlib
import socket


@contextlib.asynccontextmanager
async def running_server(configure=None, **plane_overrides):
    """In-process server on a free port (createSingleNodeServer analog).

    An async context manager rather than a pytest fixture: the conftest
    async shim runs coroutine *tests*, not async fixtures. `configure`
    (optional callable) mutates the Config before the server is built.
    """
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cfg = make_config(port, **plane_overrides)
    if configure is not None:
        configure(cfg)
    srv = create_server(cfg)
    await srv.start()
    try:
        yield srv
    finally:
        await srv.stop(force=True)


async def test_health_and_validate():
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{server.port}/") as r:
                assert r.status == 200
            async with s.get(
                f"http://127.0.0.1:{server.port}/rtc/validate?access_token={token('a', 'r')}"
            ) as r:
                assert r.status == 200
            async with s.get(
                f"http://127.0.0.1:{server.port}/rtc/validate?access_token=garbage"
            ) as r:
                assert r.status == 401


async def test_rtc_rejects_bad_tokens():
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{server.port}/rtc") as r:
                assert r.status == 401
            t = AccessToken(API_KEY, API_SECRET)
            t.identity = "x"
            t.grant = VideoGrant(room_list=True)  # no roomJoin
            async with s.get(
                f"http://127.0.0.1:{server.port}/rtc?access_token={t.to_jwt()}"
            ) as r:
                assert r.status == 401


async def test_join_publish_subscribe_media():
    """The TestSinglePublisher flow end-to-end over the wire."""
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, server.port)
            bob = SignalClient(s, server.port)
            join_a = await alice.connect("lobby", "alice")
            assert join_a["participant"]["identity"] == "alice"
            join_b = await bob.connect("lobby", "bob")
            assert [p["identity"] for p in join_b["other_participants"]] == ["alice"]

            # alice announces + publishes an audio track
            await alice.send_signal("add_track", {"cid": "mic", "type": 0, "name": "mic"})
            tp = await alice.wait_for("track_published")
            track_sid = tp["track"]["sid"]

            # first media frame binds the pending track (the reference's
            # OnTrack moment); bob then auto-subscribes
            await alice.send_media(
                cid="mic", sn=99, ts=0, payload=b"bind", audio_level=20, frame_ms=20
            )
            await bob.wait_for("track_subscribed")

            # alice streams 5 packets; bob receives them munged+payload
            # intact. Flow-controlled (wait for each delivery before the
            # next send): under parallel-suite load the tick loop can stall
            # long enough that un-paced sends overflow one tick's K=4
            # packet slots and a frame drops — a harness artifact, not a
            # product property.
            for i in range(5):
                await alice.send_media(
                    cid="mic", sn=100 + i, ts=960 * i, payload=b"opus" + bytes([i]),
                    audio_level=20, frame_ms=20,
                )
                deadline = asyncio.get_event_loop().time() + 8.0
                while not any(m["sn"] == 100 + i for m in bob.media):
                    if asyncio.get_event_loop().time() > deadline:
                        raise TimeoutError(f"sn {100 + i} never delivered")
                    await asyncio.sleep(0.01)
            media = bob.media
            sns = [m["sn"] for m in media]
            assert [s for s in sns if s >= 100][:5] == [100, 101, 102, 103, 104]
            first = next(m for m in media if m["sn"] == 100)
            assert first["payload"] == b"opus\x00"
            assert first["track_sid"] == track_sid

            # speakers fire eventually (alice is loud)
            server.room_manager.sample_traffic()  # open a rate window
            for i in range(5, 40):
                await alice.send_media(
                    cid="mic", sn=100 + i, ts=960 * i, payload=b"x", audio_level=18,
                    frame_ms=20,
                )
                await asyncio.sleep(0.012)
            spk = await bob.wait_for("speakers_changed", timeout=5)
            assert spk["speakers"][0]["sid"] == join_a["participant"]["sid"]

            # Per-participant traffic accounting
            # (participant_traffic_load.go seat): alice published ~35
            # packets inside the sample window — her ingress rate is
            # nonzero and feeds the node packet rate.
            rm = server.room_manager
            rm.sample_traffic()
            traffic = rm.participant_traffic(rm.rooms["lobby"])
            assert traffic["alice"]["rx_pps"] > 0
            assert traffic["alice"]["rx_bps"] > 0
            assert rm.router.local_node.stats.packets_in_per_sec > 0

            await alice.close()
            await bob.close()


async def test_room_service_api():
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            hdr = {"Authorization": f"Bearer {admin_token('api-room')}"}
            base = f"http://127.0.0.1:{server.port}/twirp/livekit.RoomService"

            async with s.post(f"{base}/CreateRoom", json={"name": "api-room"}, headers=hdr) as r:
                assert r.status == 200
                room = await r.json()
                assert room["name"] == "api-room"

            async with s.post(f"{base}/ListRooms", json={}, headers=hdr) as r:
                rooms = (await r.json())["rooms"]
                assert "api-room" in [x["name"] for x in rooms]

            # join someone, then admin ops on them
            alice = SignalClient(s, server.port)
            await alice.connect("api-room", "alice")
            async with s.post(
                f"{base}/ListParticipants", json={"room": "api-room"}, headers=hdr
            ) as r:
                parts = (await r.json())["participants"]
                assert [p["identity"] for p in parts] == ["alice"]

            async with s.post(
                f"{base}/UpdateRoomMetadata",
                json={"room": "api-room", "metadata": "hello"},
                headers=hdr,
            ) as r:
                assert (await r.json())["metadata"] == "hello"
            await alice.wait_for("room_update")

            async with s.post(
                f"{base}/RemoveParticipant",
                json={"room": "api-room", "identity": "alice"},
                headers=hdr,
            ) as r:
                assert r.status == 200
            await alice.wait_for("leave")

            async with s.post(f"{base}/DeleteRoom", json={"room": "api-room"}, headers=hdr) as r:
                assert r.status == 200
            await alice.close()

            # non-admin token refused
            async with s.post(
                f"{base}/DeleteRoom",
                json={"room": "x"},
                headers={"Authorization": f"Bearer {token('u', 'x')}"},
            ) as r:
                assert r.status == 403

            # admin of room A must NOT administrate room B
            # (auth.go:140 room-scoped EnsureAdminPermission)
            async with s.post(
                f"{base}/ListParticipants",
                json={"room": "other-room"},
                headers={"Authorization": f"Bearer {admin_token('api-room')}"},
            ) as r:
                assert r.status == 403

            # ...and a roomAdmin token with no room claim scopes to nothing
            async with s.post(
                f"{base}/SendData",
                json={"room": "api-room", "data": "x"},
                headers={"Authorization": f"Bearer {admin_token()}"},
            ) as r:
                assert r.status == 403


async def test_full_room_allows_same_identity_rejoin():
    """max_participants must not count the stale session a same-identity
    rejoin replaces (crash-reconnect without the reconnect flag)."""
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            hdr = {"Authorization": f"Bearer {admin_token()}"}
            base = f"http://127.0.0.1:{server.port}/twirp/livekit.RoomService"
            async with s.post(
                f"{base}/CreateRoom",
                json={"name": "capped", "max_participants": 1},
                headers=hdr,
            ) as r:
                assert r.status == 200

            c1 = SignalClient(s, server.port)
            await c1.connect("capped", "alice")
            # a different identity is rejected (leave with JOIN_FAILURE)
            c2 = SignalClient(s, server.port)
            c2.ws = await s.ws_connect(
                f"ws://127.0.0.1:{server.port}/rtc?access_token={token('bob', 'capped')}"
            )
            c2._reader = asyncio.ensure_future(c2._read())
            leave = await c2.wait_for("leave")
            assert leave["reason"] == int(7)  # JOIN_FAILURE
            # same identity rejoins fine; the old session is kicked
            c3 = SignalClient(s, server.port)
            await c3.connect("capped", "alice")
            dup = await c1.wait_for("leave")
            assert dup["reason"] == 2  # DUPLICATE_IDENTITY
            await c1.close()
            await c2.close()
            await c3.close()


async def test_duplicate_identity_over_wire():
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            c1 = SignalClient(s, server.port)
            await c1.connect("dup", "alice")
            c2 = SignalClient(s, server.port)
            await c2.connect("dup", "alice")
            leave = await c1.wait_for("leave")
            assert leave["reason"] == 2  # DUPLICATE_IDENTITY
            await c1.close()
            await c2.close()


async def test_metrics_and_debug():
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, server.port)
            await alice.connect("m", "alice")
            async with s.get(f"http://127.0.0.1:{server.port}/metrics") as r:
                text = await r.text()
                assert "livekit_events_total" in text
            async with s.get(f"http://127.0.0.1:{server.port}/debug/rooms") as r:
                dbg = await r.json()
                assert "m" in dbg["rooms"]
                assert dbg["rooms"]["m"]["participants"] == ["alice"]
            # Twirp request hooks (service/server.go Twirp options): a call
            # through /twirp shows up in the status counter.
            from livekit_server_tpu.auth import AccessToken, VideoGrant

            t = AccessToken(API_KEY, API_SECRET)
            t.grant = VideoGrant(room_list=True)
            hdr = {"Authorization": f"Bearer {t.to_jwt()}"}
            base = f"http://127.0.0.1:{server.port}/twirp/livekit.RoomService"
            async with s.post(f"{base}/ListRooms", json={}, headers=hdr) as r:
                pass
            async with s.get(f"http://127.0.0.1:{server.port}/metrics") as r:
                text = await r.text()
                assert 'livekit_twirp_requests_total{method="ListRooms"' in text
            # §5.1 profiling surfaces.
            async with s.get(f"http://127.0.0.1:{server.port}/debug/tasks") as r:
                assert (await r.json())["count"] > 0
            async with s.get(f"http://127.0.0.1:{server.port}/debug/ticks") as r:
                assert "stats" in await r.json()
            await alice.close()


async def test_trace_and_blackbox_endpoints():
    from livekit_server_tpu.telemetry import trace_export

    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, server.port)
            await alice.connect("fr", "alice")
            await asyncio.sleep(0.15)  # let a few ticks record
            url = f"http://127.0.0.1:{server.port}"
            async with s.get(f"{url}/debug/trace?ticks=32") as r:
                doc = await r.json()
                events = doc["traceEvents"]
                assert events and trace_export.validate(events) == []
                assert {e["name"] for e in events} >= {
                    "stage_host", "device_step", "fan_out"
                }
            # room lane: the join emitted a lifecycle event
            async with s.get(f"{url}/debug/blackbox/fr") as r:
                bb = await r.json()
                assert any(e["event"] == "join" for e in bb["events"])
            async with s.get(f"{url}/debug/blackbox/node") as r:
                assert (await r.json())["room"] == "node"
            async with s.get(f"{url}/debug/blackbox/no-such-room") as r:
                assert r.status == 404
            await alice.close()


async def test_udp_media_through_full_server():
    """Publisher announces a UDP track via signal, streams plain RTP to the
    node's UDP port; subscriber proves address ownership via the punch
    handshake and receives rewritten RTP (the native-transport version of
    TestSinglePublisher)."""
    import socket

    from livekit_server_tpu.runtime.udp import PUNCH_ACK, PUNCH_REQ
    from tests.test_native import rtp_packet

    async with running_server() as server:
        udp_port = server.config.rtc.udp_port
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, server.port)
            bob = SignalClient(s, server.port)
            await alice.connect("udp-room", "alice")
            await bob.connect("udp-room", "bob")

            await alice.send_signal(
                "add_track", {"cid": "mic", "type": 0, "name": "m", "transport": "udp"}
            )
            rr = await alice.wait_for("request_response")
            ssrc = rr["udp_media"]["ssrc"]
            track_sid = rr["udp_media"]["track_sid"]
            await bob.wait_for("track_subscribed")

            sub_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sub_sock.bind(("127.0.0.1", 0))
            sub_sock.setblocking(False)
            # Request UDP egress: the server answers with a punch id, never
            # trusting a client-supplied address (reflection hardening).
            await bob.send_signal(
                "subscription",
                {"track_sids": [track_sid], "subscribe": True, "udp": True},
            )
            rr = await bob.wait_for("request_response")
            punch_id = rr["udp_punch"]["punch_id"]
            # Prove address ownership from the real receiving socket.
            sub_sock.sendto(
                PUNCH_REQ + int(punch_id).to_bytes(4, "big"), ("127.0.0.1", udp_port)
            )
            deadline = asyncio.get_event_loop().time() + 2
            ack = b""
            while asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
                try:
                    ack, _ = sub_sock.recvfrom(2048)
                    break
                except BlockingIOError:
                    continue
            assert ack == PUNCH_ACK + int(punch_id).to_bytes(4, "big")

            pub_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            got = []
            for i in range(8):
                pub_sock.sendto(
                    rtp_packet(sn=900 + i, ts=960 * i, ssrc=ssrc, audio_level=25,
                               payload=b"udp-opus" + bytes([i])),
                    ("127.0.0.1", udp_port),
                )
                await asyncio.sleep(0.03)
                while True:
                    try:
                        data, _ = sub_sock.recvfrom(2048)
                        if not (192 <= data[1] <= 223):  # skip RTCP SRs
                            got.append(data)
                    except BlockingIOError:
                        break
            deadline = asyncio.get_event_loop().time() + 3
            while len(got) < 8 and asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
                while True:
                    try:
                        data, _ = sub_sock.recvfrom(2048)
                        if not (192 <= data[1] <= 223):  # skip RTCP SRs
                            got.append(data)
                    except BlockingIOError:
                        break
            assert len(got) == 8, f"got {len(got)} packets"
            import numpy as np

            from livekit_server_tpu.native import rtp as parser

            sns = []
            for data in got:
                out = parser.parse_batch(
                    data, np.asarray([0], np.int32), np.asarray([len(data)], np.int32)
                )[0]
                sns.append(int(out["sn"]))
                off, ln = int(out["payload_off"]), int(out["payload_len"])
                assert data[off : off + ln].startswith(b"udp-opus")
            assert sns == list(range(900, 908))

            # Telemetry depth under load: quality histograms and per-track
            # analytics move once the ~1 s stats window rolls (VERDICT #9;
            # prometheus/packets.go + statsworker.go seats).
            deadline = asyncio.get_event_loop().time() + 4
            seen_hist = seen_stats = False
            while not (seen_hist and seen_stats):
                assert asyncio.get_event_loop().time() < deadline, (
                    "histograms/analytics never moved under load"
                )
                pub_sock.sendto(
                    rtp_packet(sn=950, ts=96000, ssrc=ssrc, audio_level=25,
                               payload=b"late"),
                    ("127.0.0.1", udp_port),
                )
                await asyncio.sleep(0.2)
                async with s.get(f"http://127.0.0.1:{server.port}/metrics") as r:
                    text = await r.text()
                    assert "livekit_forward_latency_ms_count" in text
                    assert "livekit_media_tx_total" in text
                    for line in text.splitlines():
                        if line.startswith("livekit_track_bitrate_kbps_count"):
                            seen_hist = float(line.split()[-1]) > 0
                async with s.get(
                    f"http://127.0.0.1:{server.port}/debug/analytics"
                ) as r:
                    stats = (await r.json())["track_stats"]
                    seen_stats = any(
                        rec["track"] == track_sid and rec["bps"] > 0
                        for rec in stats
                    )
            pub_sock.close()
            sub_sock.close()
            await alice.close()
            await bob.close()


async def test_encrypted_udp_media_through_full_server():
    """Production wire: join hands each participant an AEAD media key over
    the authenticated WS; all UDP media (punch, RTP, egress) is sealed,
    and cleartext datagrams are dropped (require_encryption default)."""
    import base64
    import socket

    import numpy as np

    from livekit_server_tpu.native import rtp as parser
    from livekit_server_tpu.runtime.crypto import MediaCryptoClient
    from livekit_server_tpu.runtime.udp import PUNCH_ACK, PUNCH_REQ
    from tests.test_native import rtp_packet

    async with running_server(require_encryption=True) as server:
        udp_port = server.config.rtc.udp_port
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, server.port)
            bob = SignalClient(s, server.port)
            join_a = await alice.connect("sec-room", "alice")
            join_b = await bob.connect("sec-room", "bob")
            for j in (join_a, join_b):
                assert j["media_crypto"]["algo"] == "aes-128-gcm"
            a_crypt = MediaCryptoClient(
                join_a["media_crypto"]["key_id"],
                base64.b64decode(join_a["media_crypto"]["key"]),
            )
            b_crypt = MediaCryptoClient(
                join_b["media_crypto"]["key_id"],
                base64.b64decode(join_b["media_crypto"]["key"]),
            )

            await alice.send_signal(
                "add_track", {"cid": "mic", "type": 0, "name": "m", "transport": "udp"}
            )
            rr = await alice.wait_for("request_response")
            ssrc = rr["udp_media"]["ssrc"]
            track_sid = rr["udp_media"]["track_sid"]
            await bob.wait_for("track_subscribed")

            sub_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sub_sock.bind(("127.0.0.1", 0))
            sub_sock.setblocking(False)
            await bob.send_signal(
                "subscription",
                {"track_sids": [track_sid], "subscribe": True, "udp": True},
            )
            rr = await bob.wait_for("request_response")
            punch_id = rr["udp_punch"]["punch_id"]
            # Sealed punch — a cleartext one would be dropped.
            sub_sock.sendto(
                b_crypt.seal(PUNCH_REQ + int(punch_id).to_bytes(4, "big")),
                ("127.0.0.1", udp_port),
            )
            deadline = asyncio.get_event_loop().time() + 2
            ack = None
            while asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
                try:
                    ack = b_crypt.open(sub_sock.recvfrom(2048)[0])
                    break
                except BlockingIOError:
                    continue
            assert ack == PUNCH_ACK + int(punch_id).to_bytes(4, "big")

            pub_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            got = []
            for i in range(6):
                pub_sock.sendto(
                    a_crypt.seal(
                        rtp_packet(sn=910 + i, ts=960 * i, ssrc=ssrc,
                                   payload=b"sealed" + bytes([i]))
                    ),
                    ("127.0.0.1", udp_port),
                )
                await asyncio.sleep(0.04)
                while True:
                    try:
                        inner = b_crypt.open(sub_sock.recvfrom(4096)[0])
                        if inner is not None and not (192 <= inner[1] <= 223):
                            got.append(inner)
                    except BlockingIOError:
                        break
            deadline = asyncio.get_event_loop().time() + 3
            while len(got) < 6 and asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
                while True:
                    try:
                        inner = b_crypt.open(sub_sock.recvfrom(4096)[0])
                        if inner is not None and not (192 <= inner[1] <= 223):
                            got.append(inner)
                    except BlockingIOError:
                        break
            assert len(got) == 6, f"got {len(got)} packets"
            for i, m in enumerate(got):
                out = parser.parse_batch(
                    m, np.asarray([0], np.int32), np.asarray([len(m)], np.int32)
                )[0]
                assert int(out["sn"]) == 910 + i
                off, ln = int(out["payload_off"]), int(out["payload_len"])
                assert m[off : off + ln] == b"sealed" + bytes([i])

            # Cleartext media is rejected on the secure wire.
            pub_sock.sendto(
                rtp_packet(sn=999, ssrc=ssrc, payload=b"plain"),
                ("127.0.0.1", udp_port),
            )
            await asyncio.sleep(0.05)
            assert server.room_manager.udp.stats["plaintext_drop"] >= 1
            pub_sock.close()
            sub_sock.close()
            await alice.close()
            await bob.close()
