"""Sharded egress plane: seal parity, shard determinism, wire order.

The egress plane (runtime/egress_plane.py + native/egress.cpp's
egress_plane_send + native/munge.cpp's munge_walk_multi) re-runs the
one-shot native egress walk as room-aligned shards on a persistent
worker pool, with multicast-shaped canonical staging (stage the packet
bytes once per (room, track, k) group, patch per-subscriber headers,
seal per datagram). None of that may change a single wire byte:

* seal parity — every sealed datagram must be bit-identical to the
  Python reference seal in runtime/crypto.py (frame layout, nonce
  derivation, AAD coverage);
* shard determinism — the output buffer must be identical across shard
  plans and with canonical grouping on or off;
* wire order — within one (room, sub, track) stream, datagrams must
  leave in k (packet) order so SNs never reorder on the host;
* walk_multi ≡ walk — the sharded munge walker must produce the same
  columns AND the same evolved state as the single walk.
"""

import time

import numpy as np
import pytest

from livekit_server_tpu import native
from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime.egress_plane import EgressPlane, resolve_shards
from livekit_server_tpu.runtime.munge import HostMunger

SEAL_OVERHEAD = 30
HDR = 12


def _batch(n_rooms=4, subs=3, tracks=2, pkts=3, payload_len=48, sealed=True):
    """Destination-major synthetic batch (the udp staging order) in the
    exact argument shape of NativeEgress.send_sharded."""
    rng = np.random.default_rng(5)
    n = n_rooms * subs * tracks * pkts
    slab = rng.integers(0, 256, pkts * payload_len, np.uint8)
    rr = np.repeat(np.arange(n_rooms, dtype=np.int32), subs * tracks * pkts)
    ss = np.tile(np.repeat(np.arange(subs, dtype=np.int32), tracks * pkts),
                 n_rooms)
    tt = np.tile(np.repeat(np.arange(tracks, dtype=np.int32), pkts),
                 n_rooms * subs)
    kk = np.tile(np.arange(pkts, dtype=np.int32), n_rooms * subs * tracks)
    n_sess = n_rooms * subs
    keys = rng.integers(0, 256, (n_sess, 16), np.uint8)
    args = dict(
        slab=slab,
        pay_off=(kk.astype(np.int64) * payload_len),
        pay_len=np.full(n, payload_len, np.int32),
        marker=(kk == pkts - 1).astype(np.uint8),
        pt=np.full(n, 96, np.uint8),
        vp8=np.zeros(n, np.uint8),  # parity tests want untouched payloads
        sn=((rr.astype(np.int64) * 131 + tt * 17 + kk) & 0xFFFF).astype(np.uint16),
        ts=(kk.astype(np.uint32) * 3000 + rr.astype(np.uint32)),
        ssrc=((rr.astype(np.uint32) << 16) | (ss.astype(np.uint32) << 4)
              | tt.astype(np.uint32)),
        pid=np.full(n, 77, np.int32), tl0=np.full(n, 3, np.int32),
        kidx=np.full(n, 1, np.int32),
        ip=np.full(n, 0x7F000001, np.uint32),
        port=np.full(n, 50555, np.uint16),
        seal=np.full(n, 1 if sealed else 0, np.uint8),
        key_idx=(rr * subs + ss).astype(np.int32),
        keys=keys,
        key_ids=np.arange(100, 100 + n_sess, dtype=np.uint32),
        counters=(np.arange(n, dtype=np.uint64) % np.uint64(pkts * tracks)),
        rooms=rr,
    )
    return args, (rr, ss, tt, kk), keys


def _send(plane_obj, args, cols):
    rr, ss, tt, kk = cols
    tracks = int(tt.max()) + 1
    pkts = int(kk.max()) + 1
    flat_rtk = rr.astype(np.int64) * (tracks * pkts) + tt * pkts + kk
    grp, grp_slots = plane_obj.group_slots(flat_rtk, tt, kk, tracks, pkts)
    if grp is None:
        grp = np.full(len(rr), -1, np.int32)
        grp_slots = 0
    lo, hi = plane_obj.entry_plan(rr)
    return native.egress.send_sharded(
        fd=-1, shard_lo=lo, shard_hi=hi, grp=grp, grp_slots=grp_slots,
        **args,
    )


needs_native = pytest.mark.skipif(
    native.egress is None or native.munge is None,
    reason="native toolchain unavailable",
)


@needs_native
def test_native_smoke_clean():
    """The CI gate's native smoke (tools.check) must be clean here too:
    builds load, ABI version symbols match the ctypes layer, and a tiny
    build/walk runs through each library."""
    assert native.native_smoke() == []


@needs_native
def test_seal_parity_native_vs_python():
    """Every sealed datagram out of the sharded native walk must be
    byte-identical to runtime/crypto.py's reference seal: same 14-byte
    header (magic, key_id, dir=S2C, counter), same nonce derivation,
    same AAD coverage, same AES-GCM tag."""
    from livekit_server_tpu.runtime import crypto

    if not crypto.HAVE_AEAD:
        pytest.skip("no AEAD backend")
    args, cols, keys = _batch(sealed=True)
    ep = EgressPlane(shards=2)
    out, out_off, out_len, sent, *_ = _send(ep, args, cols)
    n = len(args["pay_off"])
    assert sent == n
    for i in range(n):
        dgram = bytes(out[out_off[i]:out_off[i] + out_len[i]])
        pay_off = int(args["pay_off"][i])
        payload = bytes(args["slab"][pay_off:pay_off + int(args["pay_len"][i])])
        hdr = bytes([
            0x80, int(args["pt"][i]) | (int(args["marker"][i]) << 7),
        ]) + int(args["sn"][i]).to_bytes(2, "big") \
            + int(args["ts"][i]).to_bytes(4, "big") \
            + int(args["ssrc"][i]).to_bytes(4, "big")
        sess = int(args["key_idx"][i])
        aead = crypto.AESGCM(bytes(keys[sess]))
        expect = crypto._seal(
            aead, int(args["key_ids"][sess]), crypto.DIR_S2C,
            int(args["counters"][i]), hdr + payload,
        )
        assert dgram == expect, f"entry {i}: sealed frame mismatch"


@needs_native
def test_seal_parity_client_opens():
    """The client side of the same contract: MediaCryptoClient.open()
    accepts every native-sealed datagram and returns the clear packet."""
    from livekit_server_tpu.runtime import crypto

    if not crypto.HAVE_AEAD:
        pytest.skip("no AEAD backend")
    args, cols, keys = _batch(n_rooms=2, subs=2, sealed=True)
    ep = EgressPlane(shards=2)
    out, out_off, out_len, sent, *_ = _send(ep, args, cols)
    clients = {
        s: crypto.MediaCryptoClient(int(args["key_ids"][s]), bytes(keys[s]))
        for s in range(len(keys))
    }
    for i in range(len(args["pay_off"])):
        dgram = bytes(out[out_off[i]:out_off[i] + out_len[i]])
        clear = clients[int(args["key_idx"][i])].open(dgram)
        assert clear is not None, f"entry {i}: client rejected native seal"
        assert clear[2:4] == int(args["sn"][i]).to_bytes(2, "big")


@needs_native
@pytest.mark.parametrize("sealed", [False, True])
def test_shard_determinism(sealed):
    """The output buffer must be bit-identical across shard plans and
    with canonical grouping on or off — sharding and the multicast-shaped
    staging are pure execution strategies, never semantics."""
    ref = None
    for shards in (1, 2, 3):
        for multicast in (False, True):
            args, cols, _ = _batch(n_rooms=5, subs=4, pkts=4, sealed=sealed)
            ep = EgressPlane(shards=shards, multicast_seal=multicast)
            out, out_off, out_len, sent, s_sent, s_built, s_ns = _send(
                ep, args, cols
            )
            assert sent == len(args["pay_off"])
            assert int(s_built.sum()) == sent
            cur = (bytes(out), out_off.tobytes(), out_len.tobytes())
            if ref is None:
                ref = cur
            else:
                assert cur == ref, (
                    f"shards={shards} multicast={multicast} diverged"
                )


@needs_native
def test_wire_order_preserved_per_stream():
    """Within one (room, sub, track) stream the out buffer must hold
    datagrams in k order (ascending offsets == send order within a
    shard), so sequence numbers never leave the host reordered."""
    args, cols, _ = _batch(n_rooms=3, subs=3, tracks=2, pkts=5, sealed=False)
    rr, ss, tt, kk = cols
    ep = EgressPlane(shards=3)
    out, out_off, out_len, sent, *_ = _send(ep, args, cols)
    for r in range(3):
        for s in range(3):
            for t in range(2):
                m = (rr == r) & (ss == s) & (tt == t)
                offs = out_off[m]
                ks = kk[m]
                # Entries are staged k-ascending; their buffer offsets
                # (== send order) must be k-ascending too.
                assert (np.diff(ks[np.argsort(offs)]) > 0).all()
                # And the wire SN at each offset matches the staged SN.
                for off, sn in zip(offs, args["sn"][m]):
                    assert bytes(out[off + 2:off + 4]) == int(sn).to_bytes(2, "big")


@needs_native
def test_walk_multi_matches_single_walk():
    """The sharded munge walker must produce identical egress columns AND
    identical evolved state to the single-threaded walk — rooms are the
    state-ownership unit, so whole-room shards may never change a bit."""
    import jax.numpy as jnp

    from livekit_server_tpu.models.plane import _pack_bits
    from tests.test_host_munge import _random_tick

    R, T, K, S = 6, 3, 4, 37
    dims = plane.PlaneDims(R, T, K, S)
    rng = np.random.default_rng(23)
    h_one = HostMunger(dims)
    h_multi = HostMunger(dims)
    ep = EgressPlane(shards=3)
    r_lo, r_hi = ep.room_plan(R)
    assert len(r_lo) == 3
    for _ in range(4):
        sn, ts, ts_jump, pid, tl0, ki, begin, valid, fwd, drop, switch = (
            _random_tick(rng, R, T, K, S)
        )
        fwd &= valid[..., None]
        drop &= valid[..., None] & ~fwd
        switch &= fwd
        bits = [
            np.asarray(_pack_bits(jnp.asarray(m))) for m in (fwd, drop, switch)
        ]
        a = h_one.apply_columns(sn, ts, ts_jump, pid, tl0, ki, begin, valid,
                                *bits)
        b = h_multi.apply_columns(sn, ts, ts_jump, pid, tl0, ki, begin, valid,
                                  *bits, shard_plan=(r_lo, r_hi))
        for col_a, col_b in zip(a, b):
            np.testing.assert_array_equal(col_a, col_b)
        # Per-shard counts partition the total and cover every entry.
        assert int(h_multi.last_shard_counts.sum()) == len(b[0])
    for f in HostMunger.FIELDS:
        np.testing.assert_array_equal(
            getattr(h_one, f), getattr(h_multi, f), err_msg=f
        )


# -- plan + orchestrator unit behavior ---------------------------------------

def test_room_plan_covers_all_rooms():
    ep = EgressPlane(shards=4)
    lo, hi = ep.room_plan(10)
    assert lo[0] == 0 and hi[-1] == 10
    assert (lo[1:] == hi[:-1]).all()          # contiguous
    assert ((hi - lo) >= 1).all()


def test_entry_plan_is_room_aligned():
    ep = EgressPlane(shards=3)
    rooms = np.repeat(np.arange(5, dtype=np.int32), [1, 7, 2, 9, 3])
    lo, hi = ep.entry_plan(rooms)
    assert lo[0] == 0 and hi[-1] == len(rooms)
    assert (lo[1:] == hi[:-1]).all()
    for cut in lo[1:]:
        # Every interior cut lands on the first entry of a room.
        assert rooms[cut] != rooms[cut - 1]


def test_entry_plan_single_room_collapses():
    ep = EgressPlane(shards=4)
    rooms = np.zeros(50, np.int32)
    lo, hi = ep.entry_plan(rooms)
    assert len(lo) == 1 and lo[0] == 0 and hi[0] == 50


def test_group_slots_marks_reused_packets():
    ep = EgressPlane(shards=1, multicast_seal=True)
    tracks, pkts = 2, 2
    # room 0: two subs share (t0, k0); room 1: one lone sub.
    rr = np.array([0, 0, 1], np.int32)
    tt = np.array([0, 0, 1], np.int32)
    kk = np.array([0, 0, 0], np.int32)
    flat = rr.astype(np.int64) * (tracks * pkts) + tt * pkts + kk
    grp, slots = ep.group_slots(flat, tt, kk, tracks, pkts)
    assert slots == tracks * pkts
    assert grp[0] == grp[1] == 0          # shared canonical slot t*K+k
    assert grp[2] == -1                   # lone entry: direct build
    off = EgressPlane(shards=1, multicast_seal=False)
    assert off.group_slots(flat, tt, kk, tracks, pkts) == (None, 0)


def test_resolve_shards_bounds():
    assert resolve_shards(1) == 1
    assert resolve_shards(16) == 16
    assert resolve_shards(64) == 16       # hard cap
    assert 1 <= resolve_shards(0) <= 8    # auto: local cores, capped


def test_record_send_feeds_pps_and_observe():
    ep = EgressPlane(shards=2)
    lo = np.array([0, 3], np.int64)
    hi = np.array([3, 6], np.int64)
    ep.record_send(6, 4, 6, lo, hi,
                   np.array([3, 3], np.int64), np.array([3, 3], np.int64),
                   np.array([1_000_000, 2_000_000], np.int64))
    obs = ep.observe()
    assert obs["entries"] == 6 and obs["datagrams"] == 6
    assert obs["grouped_entries"] == 4
    # EMA pps over the CRITICAL PATH (max shard ns), not the sum.
    assert obs["host_egress_pps"] == pytest.approx(6 / 2e-3, rel=0.01)
    assert len(obs["last_send"]["shards"]) == 2


def test_config_egress_section():
    from livekit_server_tpu.config.config import (
        Config,
        ConfigError,
        _validate,
    )

    cfg = Config()
    assert cfg.egress.shards == 0
    assert cfg.egress.multicast_seal is True
    cfg.egress.shards = 65
    with pytest.raises(ConfigError):
        _validate(cfg)


# -- gateway handshake TTL ---------------------------------------------------

def test_gateway_reap_unit():
    """TTL reap logic without the full DTLS handshake: an aged
    handshake-incomplete peer is torn down by service_timers, an
    established one never is."""
    # The gateway module imports the interop stack (OpenSSL-backed) at
    # module level; absent in slim images like the e2e tests above.
    pytest.importorskip("cryptography")
    from livekit_server_tpu.runtime.webrtc_gateway import (
        PEER_HANDSHAKE_TTL_S,
        GatewayPeer,
        WebRtcGateway,
    )

    class _StubTransport:
        crypto = None

        def release_subscriber(self, *a):
            pass

        def release_ssrc(self, *a):
            pass

    gw = object.__new__(WebRtcGateway)
    gw.transport = _StubTransport()
    gw.peers_by_ufrag, gw.peers_by_addr, gw.peers_by_tuple = {}, {}, {}
    gw.stats = {}

    def mk_peer(ufrag, established):
        p = object.__new__(GatewayPeer)
        p.gateway, p.ufrag, p.pwd = gw, ufrag, "pw"
        p.dtls = None
        p.srtp_tx = object() if established else None
        p.srtp_rx = p.srtp_tx
        p.addr, p.addr_code = None, 0
        p.publish, p.sub, p.sub_registered = [], None, False
        p.pin_session = None
        p.created_s = time.monotonic()
        p._last_timer = 0.0
        gw.peers_by_ufrag[ufrag] = p
        return p

    fresh = mk_peer("fresh", established=False)
    stale = mk_peer("stale", established=False)
    done = mk_peer("done", established=True)
    stale.created_s -= PEER_HANDSHAKE_TTL_S + 1
    done.created_s -= PEER_HANDSHAKE_TTL_S * 10
    gw.service_timers()
    assert "fresh" in gw.peers_by_ufrag          # inside the TTL window
    assert "stale" not in gw.peers_by_ufrag      # abandoned: reaped
    assert "done" in gw.peers_by_ufrag           # established: never reaped
    assert gw.stats["peers_reaped"] == 1
    assert fresh is gw.peers_by_ufrag["fresh"]


async def test_gateway_reaps_abandoned_handshakes():
    """A peer that answered the offer but never completed DTLS must not
    hold its ufrag slot / DTLS endpoint / minted crypto session forever:
    service_timers reaps it after PEER_HANDSHAKE_TTL_S."""
    pytest.importorskip("cryptography")  # gateway DTLS needs the interop lane
    from livekit_server_tpu.runtime import webrtc_gateway
    from tests.test_gateway import _setup

    runtime, udp, gw, cli, answer, peer = await _setup(subscribe=True)
    try:
        assert peer.ufrag in gw.peers_by_ufrag
        assert not peer.srtp_ready
        # Fresh peer: within TTL, timers must NOT reap it.
        gw.service_timers()
        assert peer.ufrag in gw.peers_by_ufrag
        # Age it past the TTL; the next timer pass tears it down.
        peer.created_s = time.monotonic() - (
            webrtc_gateway.PEER_HANDSHAKE_TTL_S + 1.0
        )
        gw.service_timers()
        assert peer.ufrag not in gw.peers_by_ufrag
        assert gw.stats["peers_reaped"] == 1
        if peer.pin_session is not None:
            assert peer.pin_session.key_id not in udp.crypto.sessions
    finally:
        cli.close()
        await runtime.stop()


async def test_gateway_never_reaps_established_peers():
    """Established SRTP peers belong to the signalling plane — the TTL
    only covers the handshake window."""
    pytest.importorskip("cryptography")  # gateway DTLS needs the interop lane
    from livekit_server_tpu.runtime import webrtc_gateway
    from tests.test_gateway import _setup

    runtime, udp, gw, cli, answer, peer = await _setup(subscribe=True)
    try:
        import asyncio

        await cli.connect(answer)
        assert peer.srtp_ready
        peer.created_s = time.monotonic() - (
            webrtc_gateway.PEER_HANDSHAKE_TTL_S * 10
        )
        gw.service_timers()
        assert peer.ufrag in gw.peers_by_ufrag
        assert gw.stats.get("peers_reaped", 0) == 0
        await asyncio.sleep(0)
    finally:
        cli.close()
        await runtime.stop()
