"""Telemetry Histogram semantics + the prometheus exposition format:
bucket-edge placement, the overflow slot, numpy/scalar equivalence,
labelled rendering, and the HELP/TYPE headers."""

from __future__ import annotations

import numpy as np
import pytest

from livekit_server_tpu.config.config import Config
from livekit_server_tpu.telemetry.service import (
    _STAGE_BUCKETS,
    Histogram,
    TelemetryService,
)


def _rendered(h: Histogram, name: str = "m", labels=None) -> list[str]:
    lines: list[str] = []
    h.render(name, lines, labels)
    return lines


# -- bucket math ------------------------------------------------------------

def test_bucket_edges_are_le_inclusive():
    h = Histogram((1.0, 2.0, 5.0))
    # a value exactly on an edge belongs to that bucket (le semantics)
    h.observe([1.0, 2.0, 5.0])
    assert h.counts.tolist() == [1, 1, 1, 0]
    h.observe([0.5, 1.5, 4.99])
    assert h.counts.tolist() == [2, 2, 2, 0]


def test_overflow_slot_feeds_inf_only():
    h = Histogram((1.0, 2.0))
    h.observe([3.0, 100.0])
    assert h.counts.tolist() == [0, 0, 2]
    lines = _rendered(h)
    assert 'm_bucket{le="1"} 0' in lines
    assert 'm_bucket{le="2"} 0' in lines
    assert 'm_bucket{le="+Inf"} 2' in lines


def test_numpy_batch_equals_scalar_loop():
    vals = [0.3, 1.0, 1.7, 2.0, 9.0, 0.0]
    ha = Histogram((0.5, 1.0, 2.0, 5.0))
    hb = Histogram((0.5, 1.0, 2.0, 5.0))
    ha.observe(np.asarray(vals, np.float64))
    for v in vals:
        hb.observe(v)
    assert ha.counts.tolist() == hb.counts.tolist()
    assert ha.count == hb.count == len(vals)
    assert ha.sum == pytest.approx(hb.sum) == pytest.approx(sum(vals))


def test_empty_observe_is_a_noop():
    h = Histogram((1.0,))
    h.observe(np.array([]))
    assert h.count == 0 and h.sum == 0.0 and h.counts.tolist() == [0, 0]


# -- render format ----------------------------------------------------------

def test_render_is_cumulative_and_complete():
    h = Histogram((1.0, 2.0, 5.0))
    h.observe([0.5, 1.5, 1.6, 3.0, 99.0])
    lines = _rendered(h, "lat")
    assert lines == [
        'lat_bucket{le="1"} 1',
        'lat_bucket{le="2"} 3',
        'lat_bucket{le="5"} 4',
        'lat_bucket{le="+Inf"} 5',
        "lat_sum 105.6",
        "lat_count 5",
    ]


def test_render_with_labels_precedes_le():
    h = Histogram((1.0,))
    h.observe([0.5, 7.0])
    lines = _rendered(h, "lat", {"stage": "device"})
    assert lines == [
        'lat_bucket{stage="device",le="1"} 1',
        'lat_bucket{stage="device",le="+Inf"} 2',
        'lat_sum{stage="device"} 7.5',
        'lat_count{stage="device"} 2',
    ]


# -- service wiring ---------------------------------------------------------

def test_wire_stages_feed_forward_latency_from_total_only():
    telem = TelemetryService(Config())
    telem.observe_wire_stages({
        "staging": np.array([1.0, 2.0], np.float32),
        "total": np.array([5.0, 6.0, 7.0], np.float32),
        "express": np.array([0.4], np.float32),
    })
    fwd = telem.histograms["livekit_forward_latency_ms"]
    # express already rides 'total' (the sampler pushes both): counting it
    # again would double-weight the express tier
    assert fwd.count == 3
    assert telem.stage_hists["staging"].count == 2
    assert telem.stage_hists["express"].count == 1
    assert telem.stage_hists["total"].buckets.tolist() == list(
        _STAGE_BUCKETS
    )
    # empty drains create nothing
    telem.observe_wire_stages({"device": np.array([], np.float32)})
    assert "device" not in telem.stage_hists


def test_prometheus_text_headers_once_per_family():
    telem = TelemetryService(Config())
    telem.add("livekit_events_total", 1, event="room_started")
    telem.add("livekit_events_total", 1, event="room_finished")
    telem.observe_wire_stages({
        "total": np.array([3.0], np.float32),
        "device": np.array([1.0], np.float32),
    })
    text = telem.prometheus_text()
    lines = text.splitlines()
    assert lines.index("# TYPE livekit_events_total counter") == (
        lines.index("# HELP livekit_events_total Lifecycle events by type")
        + 1
    )
    # the stage family renders once, with one series per stage label
    assert text.count("# TYPE livekit_wire_latency_stage_ms histogram") == 1
    assert 'livekit_wire_latency_stage_ms_count{stage="device"} 1' in lines
    assert 'livekit_wire_latency_stage_ms_count{stage="total"} 1' in lines
    assert text.count("# TYPE livekit_forward_latency_ms histogram") == 1
    assert "livekit_forward_latency_ms_count 1" in lines
    # every HELP/TYPE pair appears at most once per family
    helps = [ln.split()[2] for ln in lines if ln.startswith("# HELP")]
    assert len(helps) == len(set(helps))


def test_plane_edge_gauges_exported():
    telem = TelemetryService(Config())
    telem.observe_plane({"sleep_bias_us": 57.3, "edge_overshoot_us": 12.5})
    text = telem.prometheus_text()
    assert "livekit_plane_sleep_bias_us 57.3" in text
    assert "livekit_plane_edge_overshoot_us 12.5" in text
    assert "# TYPE livekit_plane_sleep_bias_us gauge" in text


def test_tick_duration_histogram_fed_in_ms():
    telem = TelemetryService(Config())
    telem.observe_tick_latency(0.0042)  # 4.2 ms
    h = telem.histograms["livekit_tick_duration_ms"]
    assert h.count == 1 and h.sum == pytest.approx(4.2)
