"""Allocation algebra tests (reference: pkg/sfu/forwarder_test.go allocation cases)."""

import jax
import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import allocation as al


def _bitrates():
    # 2 tracks × 4 spatial × 4 temporal; only 3×2 layers populated for track0,
    # track1 is audio-like single layer.
    b = np.zeros((2, 4, 4), np.float32)
    b[0, 0, 0], b[0, 0, 1] = 150e3, 200e3
    b[0, 1, 0], b[0, 1, 1] = 500e3, 700e3
    b[0, 2, 0], b[0, 2, 1] = 1.5e6, 2.5e6
    b[1, 0, 0] = 32e3
    return jnp.asarray(b)


def test_optimal_layer_respects_caps():
    b = _bitrates()
    opt = al.optimal_layer(b, jnp.array([2, 3]), jnp.array([3, 3]))
    assert int(al.spatial_of(opt)[0]) == 2 and int(al.temporal_of(opt)[0]) == 1
    assert int(al.spatial_of(opt)[1]) == 0 and int(al.temporal_of(opt)[1]) == 0
    opt = al.optimal_layer(b, jnp.array([1, 3]), jnp.array([0, 3]))
    assert int(al.spatial_of(opt)[0]) == 1 and int(al.temporal_of(opt)[0]) == 0


def test_optimal_layer_none_available():
    b = jnp.zeros((1, 4, 4))
    opt = al.optimal_layer(b, jnp.array([3]), jnp.array([3]))
    assert int(opt[0]) == -1


def test_allocate_budget_rich_channel_gets_optimal():
    b = _bitrates()
    target, used, deficient = al.allocate_budget(
        b, jnp.array([3, 3]), jnp.array([3, 3]), jnp.array([False, False]), 10e6
    )
    assert int(al.spatial_of(target)[0]) == 2 and int(al.temporal_of(target)[0]) == 1
    assert int(target[1]) == 0
    assert not bool(deficient.any())
    assert abs(float(used) - (2.5e6 + 32e3)) < 1


def test_allocate_budget_constrained_downgrades():
    b = _bitrates()
    target, used, deficient = al.allocate_budget(
        b, jnp.array([3, 3]), jnp.array([3, 3]), jnp.array([False, False]), 800e3
    )
    # Track0 should land on a sub-optimal layer; track1 audio fits.
    assert bool(deficient[0])
    assert float(used) <= 800e3 + 1
    assert int(target[0]) >= 0  # minimal allocation guaranteed
    assert int(target[1]) == 0


def test_allocate_budget_starvation_pauses():
    b = _bitrates()
    target, used, deficient = al.allocate_budget(
        b, jnp.array([3, 3]), jnp.array([3, 3]), jnp.array([False, False]), 10e3
    )
    assert int(target[0]) == -1  # cannot afford even minimal video
    assert bool(deficient[0])


def test_allocate_budget_mute_skips():
    b = _bitrates()
    target, used, deficient = al.allocate_budget(
        b, jnp.array([3, 3]), jnp.array([3, 3]), jnp.array([True, False]), 10e6
    )
    assert int(target[0]) == -1
    assert not bool(deficient[0])
    assert abs(float(used) - 32e3) < 1


def test_next_higher():
    b = _bitrates()
    cur = jnp.array([al.flat_layer(0, 1), 0], jnp.int32)
    nxt, delta = al.next_higher(b, jnp.array([3, 3]), jnp.array([3, 3]), cur)
    assert int(al.spatial_of(nxt)[0]) == 1 and int(al.temporal_of(nxt)[0]) == 0
    assert abs(float(delta[0]) - (500e3 - 200e3)) < 1
    assert int(nxt[1]) == 0 and float(delta[1]) == 0  # no higher layer


def test_vmap_over_subscribers():
    b = _bitrates()
    budgets = jnp.array([10e6, 300e3], jnp.float32)
    f = jax.vmap(lambda bud: al.allocate_budget(
        b, jnp.array([3, 3]), jnp.array([3, 3]), jnp.array([False, False]), bud
    ))
    target, used, deficient = f(budgets)
    assert target.shape == (2, 2)
    assert not bool(deficient[0, 0]) and bool(deficient[1, 0])


def test_pallas_rooms_budget_matches_per_room():
    """The room-batched allocation kernel (production TPU path since the
    phase-2 hoist) is bit-equivalent to the per-room fallback."""
    rng = np.random.default_rng(13)
    for R, T, S in ((4, 5, 7), (6, 4, 33)):
        bit = (rng.random((R, T, 4, 4)) * 2e6
               * (rng.random((R, T, 4, 4)) > 0.3)).astype(np.float32)
        ms = rng.integers(-1, 4, (R, S, T)).astype(np.int32)
        mt = rng.integers(-1, 4, (R, S, T)).astype(np.int32)
        mu = rng.random((R, S, T)) < 0.2
        bud = (rng.random((R, S)) * 8e6).astype(np.float32)
        args = tuple(jnp.asarray(x) for x in (bit, ms, mt, mu, bud))
        t0, u0, d0 = al.allocate_budget_rooms(*args, use_pallas=False)
        t1, u1, d1 = al.allocate_budget_rooms(*args, interpret=True)
        assert np.array_equal(np.asarray(t0), np.asarray(t1))
        assert np.allclose(np.asarray(u0), np.asarray(u1), rtol=1e-5)
        assert np.array_equal(np.asarray(d0), np.asarray(d1))


def test_pallas_rooms_budget_edge_cases_match():
    """Kernel/fallback parity at the boundary conditions the random
    sweep rarely lands on: zero budget, every track muted, and a budget
    large enough to admit every top layer. These are the branches that
    drift silently when the two-pass greedy is edited in one place."""
    rng = np.random.default_rng(29)
    R, T, S = 3, 4, 8
    bit = (rng.random((R, T, 4, 4)) * 2e6).astype(np.float32)
    ms = np.full((R, S, T), 3, np.int32)
    mt = np.full((R, S, T), 3, np.int32)
    cases = [
        (np.zeros((R, S, T), bool), np.zeros((R, S), np.float32)),
        (np.ones((R, S, T), bool),
         (rng.random((R, S)) * 5e6).astype(np.float32)),
        (np.zeros((R, S, T), bool), np.full((R, S), 1e9, np.float32)),
    ]
    for mu, bud in cases:
        args = tuple(jnp.asarray(x) for x in (bit, ms, mt, mu, bud))
        t0, u0, d0 = al.allocate_budget_rooms(*args, use_pallas=False)
        t1, u1, d1 = al.allocate_budget_rooms(*args, interpret=True)
        assert np.array_equal(np.asarray(t0), np.asarray(t1))
        assert np.allclose(np.asarray(u0), np.asarray(u1), rtol=1e-5)
        assert np.array_equal(np.asarray(d0), np.asarray(d1))
