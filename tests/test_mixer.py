"""MCU seat: server-side Opus mixing (BASELINE config 2).

Two publishers send distinct tones as real Opus packets through the UDP
rx path; an opted-in subscriber receives ONE mixed Opus stream whose
spectrum carries BOTH tones, and a publisher-subscriber never hears
their own tone (self-exclusion). Reference stance: the reference is
SFU-only (pkg/sfu/audio/audiolevel.go) — the mix is this build's own
BASELINE commitment.
"""

import asyncio
import socket

import numpy as np
import pytest

from livekit_server_tpu.interop import opus
from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.udp import start_udp_transport

pytestmark = pytest.mark.skipif(
    not opus.available(), reason="libopus not present"
)

DIMS = plane.PlaneDims(rooms=2, tracks=3, pkts=8, subs=4)


def _tone(freq: float, frame: int) -> np.ndarray:
    t = (np.arange(960) + frame * 960) / 48000.0
    return (np.sin(2 * np.pi * freq * t) * 9000).astype(np.int16)


def _rtp(ssrc: int, sn: int, ts: int, payload: bytes) -> bytes:
    return (
        bytes([0x80, 0x80 | 111])
        + (sn & 0xFFFF).to_bytes(2, "big")
        + (ts & 0xFFFFFFFF).to_bytes(4, "big")
        + ssrc.to_bytes(4, "big")
        + payload
    )


def _spectrum_peaks(pcm: np.ndarray, freqs) -> dict:
    mag = np.abs(np.fft.rfft(pcm.astype(float)))
    f = np.fft.rfftfreq(len(pcm), 1 / 48000.0)
    noise = np.median(mag) + 1e-9
    out = {}
    for q in freqs:
        band = mag[(f > q - 60) & (f < q + 60)]
        out[q] = float(band.max() / noise) if band.size else 0.0
    return out


async def test_mixer_end_to_end_two_tones_and_self_exclusion():
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", 0)
    port = transport.transport.get_extra_info("sockname")[1]
    try:
        # Two audio publishers (tracks 0, 1); track 1's publisher is also
        # subscriber 1 (self-exclusion case); subscriber 2 is listen-only.
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_track(0, 1, published=True, is_video=False)
        ssrc_a = transport.assign_ssrc(0, 0, is_video=False)
        ssrc_b = transport.assign_ssrc(0, 1, is_video=False)

        sub_b = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub_b.bind(("127.0.0.1", 0))
        sub_b.setblocking(False)
        sub_l = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub_l.bind(("127.0.0.1", 0))
        sub_l.setblocking(False)
        transport.register_subscriber(0, 1, sub_b.getsockname())
        transport.register_subscriber(0, 2, sub_l.getsockname())

        mixer = transport.enable_audio_mixer()
        mixer.enable_sub(0, 1, exclude_track=1)   # B: never hears B
        mixer.enable_sub(0, 2)                    # listener: hears A+B

        enc_a, enc_b = opus.OpusEncoder(), opus.OpusEncoder()
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))

        dec_b, dec_l = opus.OpusDecoder(), opus.OpusDecoder()
        pcm_b, pcm_l = [], []
        for frame in range(40):
            pub.sendto(
                _rtp(ssrc_a, 100 + frame, 960 * frame,
                     enc_a.encode(_tone(440.0, frame))),
                ("127.0.0.1", port),
            )
            pub.sendto(
                _rtp(ssrc_b, 200 + frame, 960 * frame,
                     enc_b.encode(_tone(1320.0, frame))),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.004)
            mixer.tick()  # drive the frame clock deterministically
            for sock_, dec_, acc in (
                (sub_b, dec_b, pcm_b), (sub_l, dec_l, pcm_l),
            ):
                while True:
                    try:
                        d = sock_.recvfrom(4096)[0]
                    except BlockingIOError:
                        break
                    if 192 <= d[1] <= 223 or (d[1] & 0x7F) != 111:
                        continue
                    acc.append(dec_.decode(d[12:]))
        assert mixer.stats["frames_mixed"] > 10, mixer.debug_summary()
        assert len(pcm_l) > 10 and len(pcm_b) > 10
        # Listener hears BOTH tones; B hears A's tone but NOT their own.
        tail_l = np.concatenate(pcm_l[len(pcm_l) // 2 :])
        tail_b = np.concatenate(pcm_b[len(pcm_b) // 2 :])
        pk_l = _spectrum_peaks(tail_l, [440.0, 1320.0])
        pk_b = _spectrum_peaks(tail_b, [440.0, 1320.0])
        assert pk_l[440.0] > 20 and pk_l[1320.0] > 20, pk_l
        assert pk_b[440.0] > 20, pk_b
        assert pk_b[1320.0] < pk_b[440.0] / 4, pk_b
        pub.close()
        sub_b.close()
        sub_l.close()
    finally:
        if transport.audio_mixer is not None:
            transport.audio_mixer.close()
        transport.transport.close()
        await runtime.stop()


async def test_mixer_signal_opt_in_and_teardown():
    """subscription {"audio_mix": true} enables the mixer for that
    subscriber with self-exclusion; leave tears the lane down."""
    from livekit_server_tpu.protocol.signal import SignalRequest
    from livekit_server_tpu.rtc import Room, handle_participant_signal
    from livekit_server_tpu.protocol import models as pm
    from tests.test_rtc_runtime import make_participant

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", 0)
    try:
        room = Room("mix", runtime)
        room.udp = transport
        p, _sink = make_participant(room, "alice")
        room.join(p)
        handle_participant_signal(room, p, SignalRequest(
            "add_track", {"cid": "mic", "type": 0, "name": "mic"}
        ))
        track = p.publish_pending("mic")
        assert track is not None
        handle_participant_signal(room, p, SignalRequest(
            "subscription", {"track_sids": [], "audio_mix": True}
        ))
        mixer = transport.audio_mixer
        assert mixer is not None
        rm = mixer.rooms[room.slots.row]
        assert p.sub_col in rm.subs
        assert rm.subs[p.sub_col].exclude_track == track.track_col
        # Opt out via the same signal.
        handle_participant_signal(room, p, SignalRequest(
            "subscription", {"track_sids": [], "audio_mix": False}
        ))
        assert room.slots.row not in mixer.rooms
    finally:
        if transport.audio_mixer is not None:
            transport.audio_mixer.close()
        transport.transport.close()
        await runtime.stop()


async def test_mixer_exclusion_tracks_publish_order_and_release():
    """Self-exclusion stays correct when opt-in precedes publish, and a
    released track's decoder lane + stale exclusions are scrubbed."""
    from livekit_server_tpu.protocol.signal import SignalRequest
    from livekit_server_tpu.rtc import Room, handle_participant_signal
    from tests.test_rtc_runtime import make_participant

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", 0)
    try:
        room = Room("mix2", runtime)
        room.udp = transport
        p, _ = make_participant(room, "alice")
        room.join(p)
        # Opt in BEFORE publishing the mic (normal client ordering).
        handle_participant_signal(room, p, SignalRequest(
            "subscription", {"track_sids": [], "audio_mix": True}
        ))
        mixer = transport.audio_mixer
        rm = mixer.rooms[room.slots.row]
        assert rm.subs[p.sub_col].exclude_track == -1
        handle_participant_signal(room, p, SignalRequest(
            "add_track", {"cid": "mic", "type": 0, "name": "mic"}
        ))
        track = p.publish_pending("mic")
        assert rm.subs[p.sub_col].exclude_track == track.track_col
        # Feed the lane, then unpublish: lane + exclusion must be scrubbed.
        mixer.push(room.slots.row, track.track_col, 0,
                   opus.OpusEncoder().encode(_tone(440.0, 0)))
        assert track.track_col in rm.tracks
        p.unpublish_track(track.info.sid)
        assert track.track_col not in rm.tracks
        assert rm.subs[p.sub_col].exclude_track == -1
    finally:
        if transport.audio_mixer is not None:
            transport.audio_mixer.close()
        transport.transport.close()
        await runtime.stop()


async def test_mixer_opt_out_does_not_instantiate():
    from livekit_server_tpu.protocol.signal import SignalRequest
    from livekit_server_tpu.rtc import Room, handle_participant_signal
    from tests.test_rtc_runtime import make_participant

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", 0)
    try:
        room = Room("mix3", runtime)
        room.udp = transport
        p, _ = make_participant(room, "bob")
        room.join(p)
        handle_participant_signal(room, p, SignalRequest(
            "subscription", {"track_sids": [], "audio_mix": False}
        ))
        assert transport.audio_mixer is None
    finally:
        transport.transport.close()
        await runtime.stop()


class _StubTransport:
    """Just enough UDPMediaTransport surface for AudioMixer: ssrc mint,
    subscriber address book, and the _sendto chokepoint (captured)."""

    def __init__(self):
        self.sent = []
        self.sub_addrs = {}
        self.sub_sessions = {}
        self.stats = {"tx": 0}
        self._ssrc = 100

    def _new_ssrc(self):
        self._ssrc += 1
        return self._ssrc

    def _sendto(self, data, addr, session):
        self.sent.append((addr, data))


def test_device_mix_path_emits_identical_packets():
    """The batched-einsum mix path (device_mix_min_rooms crossed — the
    1000-room bench shape) must emit byte-identical Opus packets to the
    per-room host path: the mix is a layout/batching decision, not an
    audio one. Opus encode is deterministic for identical PCM, so any
    sample drift in the einsum would surface as differing payloads."""
    from livekit_server_tpu.runtime.mixer import AudioMixer

    captured = []
    for min_rooms in (1, 99):  # 1 = force device path; 99 = host path
        t = _StubTransport()
        mixer = AudioMixer(t)
        mixer.device_mix_min_rooms = min_rooms
        encs = {}
        for room in range(3):
            for sub in range(2):
                t.sub_addrs[(room, sub)] = ("127.0.0.1", 4000 + room * 8 + sub)
            mixer.enable_sub(room, 0, exclude_track=0)  # hears track 1 only
            mixer.enable_sub(room, 1)                   # hears both
            for track in range(2):
                encs[(room, track)] = opus.OpusEncoder()
        for frame in range(4):
            for (room, track), enc in encs.items():
                tone = _tone(300 + 200 * track + 40 * room, frame)
                mixer.push(room, track, frame * 960, enc.encode(tone))
            mixer.tick()
        if min_rooms == 1:
            assert mixer.stats["device_mix_frames"] == 4
        else:
            assert mixer.stats["device_mix_frames"] == 0
        assert mixer.stats["packets_out"] == 3 * 2 * 4
        captured.append(t.sent)
        mixer.close()
    assert captured[0] == captured[1]
