"""Benchmark: batched selective-forwarding on one chip, device + host path.

Primary metric: RTP packet *writes* per second — one write = actually
forwarding one packet to one subscriber, the unit of the reference's hot
path (`DownTrack.WriteRTP`, pkg/sfu/downtrack.go:680). The reference's own
in-code measurement is ~50 µs per write on a server CPU core
(pkg/sfu/downtrackspreader.go:96-98) ⇒ baseline 20,000 writes/sec/core.
Only packets the selector actually forwards are counted (drops are not).

Also reported in the same JSON line:
  - p99_forward_ms / p50_forward_ms — ingest→wire forward latency through
    the REAL host path (UDP datagram dispatch → native batch parse →
    IngestBuffer → device tick → egress rewrite → socket writes), the
    BASELINE.md stated metric. Composition: per-tick host-side time is
    measured end-to-end with the device-step time subtracted, then the
    steady-state on-device tick time (from the chained device loop, which
    does not pay the per-dispatch tunnel round trip) is added back — so a
    tunneled dev chip reports what a locally-attached chip does.
  - configs — BASELINE.md ladder configs 1-4 (device throughput each).
    Config 5 (multi-node) is exercised by the driver's dryrun_multichip.
  - mem_1k_rooms_50subs_ok — a 1k-room × 50-sub plane allocates and ticks
    on this chip (north-star memory feasibility: 10k rooms / v5e-8).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

import numpy as np

BASELINE_WRITES_PER_SEC = 20_000.0  # reference: ~50 µs per WriteRTP, 1 core


# -- device throughput ------------------------------------------------------

def device_bench(dims, spec, ticks: int, warmup: int) -> dict:
    """Chained device steps, measured as a TWO-WINDOW slope so the fixed
    per-run dispatch/sync cost (large through a tunneled dev chip, nonzero
    even locally) cancels out: per-tick time = (t(2N) − t(N)) / N over
    identical input streams."""
    import jax
    import jax.numpy as jnp

    from livekit_server_tpu.models import plane, synth

    state = synth.make_state(dims, spec)

    @jax.jit
    def step(state, fwd, evaluated, inp):
        ev = jnp.sum(
            (inp.valid[:, :, :, None] & state.ctrl.subscribed[:, :, None, :]),
            dtype=jnp.int32,
        )
        state, out = plane.media_plane_tick(state, inp)
        return state, fwd + out.fwd_packets.sum(), evaluated + ev, out.fwd_packets

    traffic = synth.init_traffic(dims, spec)
    # Inputs are pre-staged ON DEVICE: through a tunneled dev chip a
    # per-tick host upload costs ~50 ms and would swamp the compute being
    # measured (a locally-attached chip uploads in microseconds — the
    # runtime's real per-tick upload is negligible there). The HBM cost is
    # bounded: ~1 MB/tick at the default shape (~200 MB total), ~9 MB/tick
    # for the 2-tick memory-feasibility run.
    inputs = []
    for i in range(warmup + 4 * ticks):
        traffic, inp = synth.next_tick(traffic, dims, spec, tick_index=i)
        inputs.append(jax.tree.map(jnp.asarray, inp))

    fwd = jnp.zeros((), jnp.int32)
    ev = jnp.zeros((), jnp.int32)
    for i in range(warmup):
        state, fwd, ev, _ = step(state, fwd, ev, inputs[i])
    jax.block_until_ready(fwd)

    def window(state, n, start):
        fwd = jnp.zeros((), jnp.int32)
        ev = jnp.zeros((), jnp.int32)
        t0 = time.perf_counter()
        for i in range(start, start + n):
            state, fwd, ev, _ = step(state, fwd, ev, inputs[i])
        fwd = int(jax.block_until_ready(fwd))
        ev = int(jax.block_until_ready(ev))
        return state, fwd, ev, time.perf_counter() - t0

    # Window A: N ticks; window B: 3N ticks of the continuing stream.
    # t(N) = C + N·τ ⇒ τ = (t_B − t_A)/2N with the fixed cost C cancelled;
    # the 3×-vs-1× separation keeps timing jitter small relative to dt.
    state, fwd_a, ev_a, t_a = window(state, ticks, warmup)
    state, fwd_b, ev_b, t_b = window(state, 3 * ticks, warmup + ticks)
    if t_b < 1.2 * t_a:
        # Fixed cost dominates (tiny config): the slope is buried in
        # noise — report window B absolute (conservative: includes C).
        return {
            "fwd_writes_per_s": round(fwd_b / t_b, 1),
            "evaluated_per_s": round(ev_b / t_b, 1),
            "device_tick_ms": round(t_b / (3 * ticks) * 1000.0, 3),
        }
    dt = t_b - t_a
    fwd = max(fwd_b - fwd_a, 0)
    ev = max(ev_b - ev_a, 0)
    return {
        "fwd_writes_per_s": round(fwd / dt, 1),
        "evaluated_per_s": round(ev / dt, 1),
        "device_tick_ms": round(dt / (2 * ticks) * 1000.0, 3),
    }


# -- host-path forward latency ---------------------------------------------

def _vp8_descriptor(pid: int, tl0: int, tid: int, sbit: bool, keyframe: bool) -> bytes:
    """Minimal VP8 payload descriptor (X, I 15-bit pid, L, T) + the first
    payload byte whose P bit conveys keyframe-ness."""
    return bytes(
        [0x80 | (0x10 if sbit else 0), 0xE0, 0x80 | ((pid >> 8) & 0x7F),
         pid & 0xFF, tl0 & 0xFF, ((tid & 0x3) << 6) | 0x20,
         0x00 if keyframe else 0x01]
    )


def _build_tick_datagrams(ssrcs, counts, sn0, tick, spec):
    """Raw RTP datagrams for one tick (what publishers put on the wire).
    One frame per track per tick: the first packet starts the picture
    (S bit), and keyframes arrive on the device bench's cadence (1/100
    ticks) — not on every packet."""
    out = []
    for (r, t, is_video, ssrc), n in zip(ssrcs, counts):
        for k in range(n):
            sn = (sn0[(r, t)] + k) & 0xFFFF
            ts = (tick * (90 * spec.tick_ms if is_video else 48 * spec.tick_ms)) & 0xFFFFFFFF
            hdr = bytearray(12)
            hdr[0] = 0x80
            hdr[1] = (0x80 if k == n - 1 else 0) | (96 if is_video else 111)
            hdr[2:4] = sn.to_bytes(2, "big")
            hdr[4:8] = ts.to_bytes(4, "big")
            hdr[8:12] = ssrc.to_bytes(4, "big")
            if is_video:
                # Keyframes every 10 ticks: the cadence PLI-driven recovery
                # produces (the selector locks only at keyframes and the
                # bench publisher can't answer live PLIs).
                payload = _vp8_descriptor(
                    tick & 0x7FFF, tick & 0xFF, k % 2,
                    sbit=k == 0, keyframe=tick % 10 == 0 and k == 0,
                ) + bytes(1100)
            else:
                payload = bytes(80)
            out.append(bytes(hdr) + payload)
        sn0[(r, t)] = (sn0[(r, t)] + n) & 0xFFFF
    return out


async def host_path_bench(dims, spec, ticks: int, device_tick_ms: float) -> dict:
    """End-to-end through the real runtime: datagram dispatch → native
    parse → ingest → device tick → egress rewrite → UDP socket writes.

    Per-tick host time = wall time minus the (tunnel-inflated) in-loop
    device step; the chained device_tick_ms is added back for the
    reported forward latency.
    """
    import jax  # noqa: F401  (backend already selected by main)

    from livekit_server_tpu.models import plane
    from livekit_server_tpu.runtime import PlaneRuntime
    from livekit_server_tpu.runtime.udp import start_udp_transport

    import socket as _socket

    runtime = PlaneRuntime(dims, tick_ms=spec.tick_ms)
    udp = await start_udp_transport(runtime.ingest, host="127.0.0.1", port=0)

    # A loopback receiver socket so egress hits the real kernel send path.
    # Deliberately NEVER read (and not registered with asyncio): a real
    # subscriber is a remote host — an in-process Python consumer would
    # bill ~5k asyncio callbacks/tick of its own cost to the SFU's
    # forward-latency measurement. Packets beyond rcvbuf drop in-kernel.
    loop = asyncio.get_running_loop()
    sink_sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
    sink_sock.bind(("127.0.0.1", 0))
    sink_sock.setblocking(False)
    sink_addr = sink_sock.getsockname()

    nv = min(spec.video_tracks, dims.tracks)
    used = min(nv + spec.audio_tracks, dims.tracks)
    ssrcs = []
    for r in range(dims.rooms):
        for t in range(used):
            is_video = t < nv
            ssrc = udp.assign_ssrc(r, t, is_video)
            runtime.set_track(r, t, published=True, is_video=is_video)
            ssrcs.append((r, t, is_video, ssrc))
        for s in range(dims.subs):
            udp.register_subscriber(r, s, sink_addr)
            for t in range(used):
                runtime.set_subscription(r, t, s, subscribed=True)

    # Instrument the device step so the in-loop (tunnel-priced) device time
    # can be subtracted from each tick's wall time.
    dev_times = []
    orig_step = runtime._device_step

    def timed_step(inp):
        t0 = time.perf_counter()
        out = orig_step(inp)
        dev_times.append(time.perf_counter() - t0)
        return out

    runtime._device_step = timed_step
    runtime.on_tick(lambda res: udp.send_egress_batch(res.egress_batch))

    rng = np.random.default_rng(0)
    sn0 = {(r, t): int(rng.integers(0, 1 << 16)) for (r, t, _v, _s) in ssrcs}
    v_ppt = max(1, round(spec.video_kbps * 125 / 1200 / 1000 * spec.tick_ms))
    counts = [v_ppt if is_video else 1 for (_, _, is_video, _) in ssrcs]
    def stage(dgrams):
        """Pre-pack one tick's datagrams in the batch-receive layout
        (blob + offsets/lengths/src arrays — what rx_batch produces)."""
        blob = np.frombuffer(b"".join(dgrams), np.uint8)
        lens = np.array([len(d) for d in dgrams], np.int32)
        offs = np.zeros(len(dgrams), np.int32)
        np.cumsum(lens[:-1], out=offs[1:])
        ips = np.full(len(dgrams), 0x7F000001, np.uint32)
        ports = np.full(len(dgrams), 50000, np.uint16)
        return blob, offs, lens, ips, ports

    pre = [
        stage(_build_tick_datagrams(ssrcs, counts, sn0, i, spec))
        for i in range(ticks + 2)
    ]
    pre_pipe = [
        stage(_build_tick_datagrams(ssrcs, counts, sn0, ticks + 2 + i, spec))
        for i in range(max(10, ticks // 2))
    ]

    # Per-subscriber channel estimates (the REMB/TWCC samples real clients
    # send): without them the allocator has no budget and pauses video.
    est = spec.estimate_bps or 1.25 * 1000.0 * (
        spec.video_tracks * spec.video_kbps + spec.audio_tracks * spec.audio_kbps
    )

    # Host time is the SUM of the directly-timed host segments (rx/stage
    # before the device step, fan-out/egress after) rather than wall time
    # minus the in-loop device call: through a tunneled dev chip the
    # in-loop dispatch takes ~100 ms and its client-side marshaling
    # contends with the measuring thread, inflating wall-minus-device by
    # GIL-scheduling artifacts a locally-attached chip does not have. The
    # segments below are the actual serialized per-tick host work.
    host_ms = []
    sent0 = 0
    seq_t0 = time.perf_counter()
    loop = asyncio.get_running_loop()
    for i in range(ticks + 2):
        if i == 2:  # first ticks pay jit compile; time/count from here
            sent0 = udp.stats["tx"]
            seq_t0 = time.perf_counter()
        t0 = time.perf_counter()
        blob, offs, lens, ips, ports_a = pre[i]
        udp.feed_batch(blob, offs, lens, ips, ports_a, len(offs))
        udp._flush_rx()  # asyncio-path drain (no-op after feed_batch)
        runtime.ingest._estimate[:] = est
        runtime.ingest._estimate_valid[:] = True
        staged = runtime._stage()
        pre_dev = time.perf_counter() - t0
        out = await loop.run_in_executor(
            runtime._executor, runtime._device_step, staged[0]
        )
        t1 = time.perf_counter()
        runtime._mirror_probe_inputs(out)
        await runtime._complete(out, *staged)  # on_tick → send_egress inside
        post_dev = time.perf_counter() - t1
        if i >= 2:
            host_ms.append((pre_dev + post_dev) * 1000.0)
    seq_wall = time.perf_counter() - seq_t0
    sent = udp.stats["tx"] - sent0

    # Pipelined serving-loop capacity: same per-tick work through the
    # stage/dispatch/complete overlap the production _run loop uses —
    # tick budget becomes max(device, host egress) + staging.
    P = len(pre_pipe)
    pending = None
    pipe_t0 = time.perf_counter()
    for i in range(P):
        blob, offs, lens, ips, ports_a = pre_pipe[i]
        udp.feed_batch(blob, offs, lens, ips, ports_a, len(offs))
        udp._flush_rx()
        runtime.ingest._estimate[:] = est
        runtime.ingest._estimate_valid[:] = True
        staged = runtime._stage()
        fut = loop.run_in_executor(
            runtime._executor, runtime._device_step, staged[0]
        )
        if pending is not None:
            await runtime._complete(pending[0], *pending[1])
        out = await fut
        runtime._mirror_probe_inputs(out)
        pending = (out, staged)
    if pending is not None:
        await runtime._complete(pending[0], *pending[1])
    pipe_wall = time.perf_counter() - pipe_t0

    runtime._device_step = orig_step
    udp.transport.close()
    sink_sock.close()
    await runtime.stop()

    fwd = np.asarray(host_ms) + device_tick_ms
    host_p50 = float(np.percentile(host_ms, 50)) if host_ms else 0.0
    return {
        "p50_forward_ms": round(float(np.percentile(fwd, 50)), 3),
        "p99_forward_ms": round(float(np.percentile(fwd, 99)), 3),
        "host_ms_p50": round(host_p50, 3),
        "host_egress_pps": round(sent / (np.sum(host_ms) / 1000.0), 1)
        if host_ms and sent else 0.0,
        "wire_packets": int(sent),
        # Wall-clock rates below include the dev tunnel's ~100 ms dispatch
        # RTT per tick and are therefore tunnel-bound on this rig;
        # tick_hz_local_estimate is what a locally-attached chip sustains
        # (pipelined loop: host and device overlap, budget = max of both).
        "tick_hz_sequential": round(ticks / seq_wall, 1) if seq_wall else 0.0,
        "tick_hz_pipelined": round(P / pipe_wall, 1) if pipe_wall else 0.0,
        "tick_hz_local_estimate": round(
            1000.0 / max(host_p50, device_tick_ms, 1e-6), 1
        ),
    }


# -- main -------------------------------------------------------------------

def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rooms", type=int, default=128)
    ap.add_argument("--tracks", type=int, default=8)
    ap.add_argument("--pkts", type=int, default=16)
    ap.add_argument("--subs", type=int, default=16)
    ap.add_argument("--ticks", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--host-ticks", type=int, default=60)
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    ap.add_argument("--quick", action="store_true",
                    help="primary metric only (skip ladder/host/mem)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    bench_t0 = time.perf_counter()

    from livekit_server_tpu.models import plane, synth

    dims = plane.PlaneDims(args.rooms, args.tracks, args.pkts, args.subs)
    # Dense, realistic load: 4×3 Mbps simulcast video + 4 Opus tracks per
    # room at a 20 ms tick ≈ 6-7 video pkts/track/tick.
    spec = synth.TrafficSpec(video_tracks=4, audio_tracks=4, tick_ms=20,
                             video_kbps=3000)

    primary = device_bench(dims, spec, args.ticks, args.warmup)
    result = {
        "metric": "sfu_pkt_sub_writes_per_sec_per_chip",
        "value": primary["fwd_writes_per_s"],
        "unit": "writes/s",
        "vs_baseline": round(primary["fwd_writes_per_s"] / BASELINE_WRITES_PER_SEC, 2),
        "counted": "forwarded (pkt × subscriber) writes; drops excluded",
        "evaluated_per_s": primary["evaluated_per_s"],
        "device_tick_ms": primary["device_tick_ms"],
    }

    if not args.quick:
        # Host-path forward latency (BASELINE metric) at a shape within the
        # kernel UDP path's capacity: 32 rooms × 6 subs ≈ 270k wire pps.
        # The dense primary shape over-subscribes loopback by ~10× and
        # would measure socket queueing, not forwarding.
        try:
            host_dims = plane.PlaneDims(32, 8, 16, 6)
            # Enough ticks that the slope beats the fixed tunnel cost even
            # at this small shape (otherwise the fallback would fold the
            # tunnel round trip into the p99 composition).
            host_dev = device_bench(host_dims, spec, ticks=60, warmup=3)
            host = asyncio.run(
                host_path_bench(host_dims, spec, args.host_ticks,
                                host_dev["device_tick_ms"])
            )
            result.update(host)
            result["host_device_tick_ms"] = host_dev["device_tick_ms"]
        except Exception as e:  # noqa: BLE001 — a host-path failure must
            # not take down the primary metric the driver records.
            result["host_path_error"] = f"{type(e).__name__}: {e}"

        # BASELINE.md ladder configs 1-4 (device throughput, small windows).
        ladder = {
            "cfg1_1room_2p_audio": (
                plane.PlaneDims(1, 2, 8, 2),
                synth.TrafficSpec(video_tracks=0, audio_tracks=2, tick_ms=20),
            ),
            "cfg2_1room_50p_audio": (
                plane.PlaneDims(1, 50, 8, 50),
                synth.TrafficSpec(video_tracks=0, audio_tracks=50, tick_ms=20),
            ),
            "cfg3_1room_25p_vp8_simulcast": (
                plane.PlaneDims(1, 25, 16, 25),
                synth.TrafficSpec(video_tracks=25, audio_tracks=0, tick_ms=20,
                                  video_kbps=3000),
            ),
            "cfg4_1krooms_10p_mixed_svc": (
                plane.PlaneDims(1024, 10, 8, 10),
                synth.TrafficSpec(video_tracks=2, audio_tracks=8, tick_ms=20,
                                  video_kbps=1500, svc=True),
            ),
        }
        configs = {}
        for name, (d, s) in ladder.items():
            try:
                r = device_bench(d, s, ticks=15, warmup=3)
                configs[name] = r["fwd_writes_per_s"]
                configs[name + "_tick_ms"] = r["device_tick_ms"]
            except Exception as e:  # noqa: BLE001
                configs[name] = f"error: {type(e).__name__}"
        result["configs"] = configs
        result["cfg5_note"] = "multi-node sharding validated by dryrun_multichip"

        # North-star memory feasibility: 1k rooms × 50 subs on one chip.
        try:
            d = plane.PlaneDims(1024, 8, 16, 50)
            s = synth.TrafficSpec(video_tracks=2, audio_tracks=6, tick_ms=20)
            device_bench(d, s, ticks=2, warmup=1)
            result["mem_1k_rooms_50subs_ok"] = True
        except Exception as e:  # noqa: BLE001
            result["mem_1k_rooms_50subs_ok"] = False
            result["mem_error"] = f"{type(e).__name__}"

        # North-star tick: the FULL 10k-rooms × 50-subs plane on ONE chip
        # (the BASELINE target shape is 10k×50 on v5e-8; room-sharding
        # divides this by the mesh size, so single-chip-tick/8 estimates
        # the per-chip cost on the target pod). Time-guarded: the driver
        # runs this under a deadline, and a partial record beats a
        # timed-out empty one.
        if time.perf_counter() - bench_t0 < 420:
            try:
                d = plane.PlaneDims(10240, 8, 16, 50)
                s = synth.TrafficSpec(video_tracks=2, audio_tracks=6, tick_ms=20,
                                      video_kbps=1500, svc=True)
                r = device_bench(d, s, ticks=3, warmup=1)
                result["northstar_10240rooms_50subs_tick_ms"] = r["device_tick_ms"]
            except Exception as e:  # noqa: BLE001
                result["northstar_error"] = f"{type(e).__name__}"
        else:
            result["northstar_skipped"] = "bench deadline guard"

    print(json.dumps(result))


if __name__ == "__main__":
    main()
