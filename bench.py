"""Benchmark: batched selective-forwarding throughput on one chip.

Metric: RTP packet *writes* per second — one write = forwarding one packet
to one subscriber, the unit of the reference's hot path
(`DownTrack.WriteRTP`, pkg/sfu/downtrack.go:680). The reference's own
in-code measurement is ~50 µs per write on a server CPU core
(pkg/sfu/downtrackspreader.go:96-98) ⇒ baseline 20,000 writes/sec/core.
`vs_baseline` is the speedup of one TPU chip stepping the whole batched
media plane (layer selection + SN/TS/VP8 munge + stats + BWE + allocation +
active speakers per tick) over that single-core figure.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from livekit_server_tpu.models import plane, synth

BASELINE_WRITES_PER_SEC = 20_000.0  # reference: ~50 µs per WriteRTP, 1 core


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rooms", type=int, default=128)
    ap.add_argument("--tracks", type=int, default=8)
    ap.add_argument("--pkts", type=int, default=16)
    ap.add_argument("--subs", type=int, default=16)
    ap.add_argument("--ticks", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    dims = plane.PlaneDims(args.rooms, args.tracks, args.pkts, args.subs)
    # Dense, realistic load: 4×3 Mbps simulcast video + 4 Opus tracks per
    # room at a 20 ms tick ≈ 6-7 video pkts/track/tick (fills ~half the K=16
    # packet slots; the valid mask gates the rest).
    spec = synth.TrafficSpec(
        video_tracks=4, audio_tracks=4, tick_ms=20, video_kbps=3000
    )

    state = synth.make_state(dims, spec)

    @jax.jit
    def step(state, writes, inp):
        # One "write" = one (valid packet, subscribed subscriber) pair put
        # through the forwarding kernel — exactly the calls the reference
        # makes to DownTrack.WriteRTP (drops happen inside, there and here).
        evaluated = jnp.sum(
            (inp.valid[:, :, :, None] & state.ctrl.subscribed[:, :, None, :]),
            dtype=jnp.int32,
        )
        state, out = plane.media_plane_tick(state, inp)
        return state, writes + evaluated, out.fwd_packets

    # Pre-generate host inputs so host-side synthesis isn't in the timed loop
    # (the runtime overlaps ingest packing with the device tick the same way).
    traffic = synth.init_traffic(dims, spec)
    inputs = []
    for i in range(args.warmup + args.ticks):
        traffic, inp = synth.next_tick(traffic, dims, spec, tick_index=i)
        inputs.append(jax.tree.map(jnp.asarray, inp))

    writes = jnp.zeros((), jnp.int32)
    for i in range(args.warmup):
        state, writes, _ = step(state, writes, inputs[i])
    jax.block_until_ready(writes)

    writes = jnp.zeros((), jnp.int32)  # count only the timed window
    t0 = time.perf_counter()
    for i in range(args.warmup, args.warmup + args.ticks):
        state, writes, _ = step(state, writes, inputs[i])
    writes = int(jax.block_until_ready(writes))
    dt = time.perf_counter() - t0

    # Same unit as the reference's 50 µs figure: WriteRTP invocations/sec.
    value = writes / dt
    print(
        json.dumps(
            {
                "metric": "sfu_pkt_sub_writes_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "writes/s",
                "vs_baseline": round(value / BASELINE_WRITES_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
