"""Benchmark: batched selective-forwarding on one chip, device + host path.

Primary metric: RTP packet *writes* per second — one write = actually
forwarding one packet to one subscriber, the unit of the reference's hot
path (`DownTrack.WriteRTP`, pkg/sfu/downtrack.go:680). The reference's own
in-code measurement is ~50 µs per write on a server CPU core
(pkg/sfu/downtrackspreader.go:96-98) ⇒ baseline 20,000 writes/sec/core.
Only packets the selector actually forwards are counted (drops are not).

Also reported in the same JSON line:
  - p99_forward_ms / p50_forward_ms — ingest→wire forward latency through
    the REAL host path (UDP datagram dispatch → native batch parse →
    IngestBuffer → device tick → egress rewrite → socket writes), the
    BASELINE.md stated metric. Composition: per-tick host-side time is
    measured end-to-end with the device-step time subtracted, then the
    steady-state on-device tick time (from the chained device loop, which
    does not pay the per-dispatch tunnel round trip) is added back — so a
    tunneled dev chip reports what a locally-attached chip does.
  - configs — BASELINE.md ladder configs 1-4 (device throughput each).
    Config 5 (multi-node) is exercised by the driver's dryrun_multichip.
  - mem_1k_rooms_50subs_ok — a 1k-room × 50-sub plane allocates and ticks
    on this chip (north-star memory feasibility: 10k rooms / v5e-8).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import time

import numpy as np

BASELINE_WRITES_PER_SEC = 20_000.0  # reference: ~50 µs per WriteRTP, 1 core

# -- un-killable result emission -------------------------------------------
#
# The driver runs `python bench.py` under a deadline and keeps the LAST
# complete JSON line of stdout. Round 4's bench emitted one line at the
# very end and was killed first — every measured number died with it. Now:
#   * RESULT is global and re-emitted (one flushed JSON line) after every
#     section, so a kill at any point loses at most the section in flight;
#   * a total budget (BENCH_BUDGET_S env, --budget flag) is checked before
#     each section, with explicit *_skipped markers when it runs out;
#   * SIGTERM/SIGINT (what `timeout` sends first) re-emit and exit 0.

RESULT: dict = {}
_SECTION = ["startup"]
_T0 = time.perf_counter()
_BUDGET = [float(os.environ.get("BENCH_BUDGET_S", "480"))]


def emit() -> None:
    # Leading newline terminates any partial line an interrupted print
    # left behind, keeping the last stdout line parseable.
    sys.stdout.write("\n" + json.dumps(RESULT) + "\n")
    sys.stdout.flush()


def _emit_raw() -> None:
    """Async-signal-safe emit: the handler may interrupt a buffered
    sys.stdout.write, and a reentrant call into BufferedWriter raises —
    os.write to fd 1 cannot."""
    os.write(1, ("\n" + json.dumps(RESULT) + "\n").encode())


def _remaining() -> float:
    return _BUDGET[0] - (time.perf_counter() - _T0)


def section_ok(name: str, est_s: float) -> bool:
    """Gate a section on the remaining budget; record the skip if not."""
    if _remaining() < est_s:
        RESULT.setdefault("skipped", {})[name] = (
            f"budget: {_remaining():.0f}s left < ~{est_s:.0f}s needed"
        )
        emit()
        return False
    _SECTION[0] = name
    return True


def section_done(name: str, t_start: float) -> None:
    RESULT.setdefault("section_s", {})[name] = round(
        time.perf_counter() - t_start, 1
    )
    emit()


def _on_kill(signum, frame):  # noqa: ARG001
    RESULT["killed_in_section"] = _SECTION[0]
    try:
        _emit_raw()
    finally:
        os._exit(0)


def absorb_twin_json(stdout: str) -> dict:
    """Parse a twin subprocess's stdout under the last-line-JSON contract:
    the child may print anything, but its result is the LAST line that
    starts with `{` (both the CPU wire twin and the fleet traffic twin
    emit incrementally, so a timeout kill loses at most the step in
    flight). Raises ValueError when no JSON line survived — the caller
    records that as the section error rather than crashing the bench."""
    lines = [ln for ln in (stdout or "").strip().splitlines()
             if ln.startswith("{")]
    if not lines:
        raise ValueError("twin produced no JSON")
    return json.loads(lines[-1])


# -- device throughput ------------------------------------------------------

def device_bench(dims, spec, ticks: int, warmup: int) -> dict:
    """PRODUCTION tick graph (unpack_tick_inputs → media_plane_tick →
    pack_tick_outputs, state donated), measured as a `ticks`-long
    `lax.scan` per dispatch with a TWO-WINDOW slope.

    Two rig artifacts are engineered out (both burned earlier rounds):
      * per-tick HOST UPLOADS — r3/r4 staged inputs per step, so through
        the ~100 ms axon tunnel the "device tick" was mostly input
        transfer (cfg4 read 170 ms when the device was busy 5 ms). Inputs
        now land in HBM ONCE as a stacked pool; the scan body indexes it
        with a rotating cursor.
      * per-dispatch overhead — the axon client costs ~15 ms per execute
        call with this step's buffer count. Scanning `ticks` ticks inside
        one dispatch dilutes it to D/ticks, and the window slope (3 calls
        vs 1) cancels the remainder up to 2D/2N — bounded, stated, small.

    The packed output buffer is CONSUMED on-device into a checksum:
    nothing dead-code-eliminates (r3's scalar-returning variant let XLA
    drop the output path), and per-call transfer stays scalar-sized.
    """
    import functools

    import jax
    import jax.numpy as jnp

    from livekit_server_tpu.models import plane, synth

    R, T, K, S = dims
    state = synth.make_state(dims, spec)
    traffic = synth.init_traffic(dims, spec)

    # Host-built input pool, ONE upload. Target cap ~128 MB of HBM with a
    # floor of min(ticks, 8) distinct ticks — the floor dominates at very
    # large shapes (north-star: ~85 MB/tick ⇒ ~425 MB pool). The scan
    # cursor wraps, so windows beyond the pool replay traffic with live
    # state (SN replays read as late packets — selection/allocation work,
    # the measured quantity, is unaffected). A modest wrapped pool beats a
    # full distinct-tick pool: the axon client's per-call cost grows with
    # threaded-buffer payload.
    per_tick = (len(plane.PKT_FIELDS) * R * T * K + 8 * R * S + R * T) * 4
    n_want = warmup + 5 * ticks
    pool_n = max(min(ticks, 8), min(n_want, int(128e6 // max(per_tick, 1))))
    pks, fbs, tfs = [], [], []
    for i in range(pool_n):
        traffic, inp = synth.next_tick(traffic, dims, spec, tick_index=i)
        pkt, fb, tf, _, _ = plane.pack_tick_inputs(inp)
        pks.append(pkt)
        fbs.append(fb)
        tfs.append(tf)
    pool_pkt = jnp.asarray(np.stack(pks))
    pool_fb = jnp.asarray(np.stack(fbs))
    pool_tf = jnp.asarray(np.stack(tfs))
    del pks, fbs, tfs
    tick_ms_c = jnp.int32(spec.tick_ms)
    roll_c = jnp.int32(0)

    # Pools are DONATED and threaded through the returns: the axon client
    # charges per-call costs proportional to argument-buffer payload, and
    # donation keeps the handles stable (measured: pools-as-fresh-args
    # added ~12 ms/tick at cfg4; donated-threaded matches closure-constant
    # speed without baking a 0.5 GB constant into the executable).
    @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6))
    def run_window(state, fwd, ev, chk, pool_pkt, pool_fb, pool_tf, start):
        def body(carry, i):
            state, fwd, ev, chk = carry
            idx = (start + i) % pool_n
            pkt = jax.lax.dynamic_index_in_dim(pool_pkt, idx, keepdims=False)
            fb = jax.lax.dynamic_index_in_dim(pool_fb, idx, keepdims=False)
            tf = jax.lax.dynamic_index_in_dim(pool_tf, idx, keepdims=False)
            inp = plane.unpack_tick_inputs(pkt, fb, tf, tick_ms_c, roll_c)
            ev2 = jnp.sum(
                inp.valid[:, :, :, None] & state.ctrl.subscribed[:, :, None, :],
                dtype=jnp.int32,
            )
            state, out = plane.media_plane_tick(state, inp)
            buf = plane.pack_tick_outputs(out)
            # chk wraps in int32 — it exists to defeat DCE, not to be a
            # checksum of record.
            return (
                state,
                fwd + out.fwd_packets.sum(),
                ev + ev2,
                chk + buf.sum(),
            ), None

        (state, fwd, ev, chk), _ = jax.lax.scan(
            body, (state, fwd, ev, chk), jnp.arange(ticks, dtype=jnp.int32)
        )
        # ONE stacked counter array: each fetched leaf costs a full tunnel
        # round trip (~100 ms on this rig), so the window reads exactly
        # one buffer at its end.
        return state, jnp.stack([fwd, ev, chk]), pool_pkt, pool_fb, pool_tf

    pools = [pool_pkt, pool_fb, pool_tf]

    def window(state, n_calls, start):
        # Accumulators stay ON DEVICE across the window's calls and come
        # back as one buffer — a single fetch per window, identical in
        # both windows, cancelling in the slope.
        fwd = jnp.zeros((), jnp.int32)
        ev = jnp.zeros((), jnp.int32)
        chk = jnp.zeros((), jnp.int32)
        t0 = time.perf_counter()
        counters = None
        for j in range(n_calls):
            state, counters, pools[0], pools[1], pools[2] = run_window(
                state, fwd, ev, chk, *pools,
                jnp.int32((start + j * ticks) % pool_n),
            )
            fwd, ev, chk = counters[0], counters[1], counters[2]
        c = np.asarray(counters)
        return state, int(c[0]), int(c[1]), time.perf_counter() - t0

    # Warmup pays the compile + first-touch; `warmup` asks for at least
    # that many ticks of settling (rounded up to whole window calls).
    state, _, _, _ = window(state, max(1, -(-warmup // ticks)), 0)
    # Window A: 1 call (N ticks); window B: 3 calls (3N ticks).
    # t(c) = c·(D + N·τ) ⇒ τ_eff = (t_B − t_A)/2N = τ + D/N, with the
    # per-dispatch D (~15 ms on this rig, ~µs locally) diluted by N.
    state, fwd_a, ev_a, t_a = window(state, 1, ticks)
    state, fwd_b, ev_b, t_b = window(state, 3, 2 * ticks)
    if t_b < 1.2 * t_a:
        # Fixed cost dominates (tiny config): report window B absolute,
        # EXPLICITLY FLAGGED so consumers can't misread a dispatch floor
        # as the tick cost.
        return {
            "fwd_writes_per_s": round(fwd_b / t_b, 1),
            "evaluated_per_s": round(ev_b / t_b, 1),
            "device_tick_ms": round(t_b / (3 * ticks) * 1000.0, 3),
            "dispatch_bound": True,
        }
    dt = t_b - t_a
    fwd = max(fwd_b - fwd_a, 0)
    ev = max(ev_b - ev_a, 0)
    return {
        "fwd_writes_per_s": round(fwd / dt, 1),
        "evaluated_per_s": round(ev / dt, 1),
        "device_tick_ms": round(dt / (2 * ticks) * 1000.0, 3),
    }


# -- real-time wire bench ---------------------------------------------------
#
# Replaces the r3 composed p99 (VERDICT r3 missing #2 / next #1 and #4):
# the production serving loop runs at real tick cadence; publishers put
# raw RTP on the server's actual UDP socket; 1-in-6 subscribers is a
# sealed "modern" client whose egress carries TWCC counters and whose
# reader task acks them with RTPFB fmt-15 frames through the server's
# real RTCP path (_handle_twcc exercised on every feedback); the rest are
# cleartext "legacy" clients driving the estimate channel with REMB
# frames — no direct ingest._estimate injection anywhere. Per-packet
# forward latency comes from the always-on ForwardLatencyProbe
# (recvmmsg-return → native-send-return), so the reported p50/p99 are
# wall-clock measurements that INCLUDE tick-queueing wait.

def _vp8_descriptor(pid: int, tl0: int, tid: int, sbit: bool, keyframe: bool) -> bytes:
    """Minimal VP8 payload descriptor (X, I 15-bit pid, L, T) + the first
    payload byte whose P bit conveys keyframe-ness."""
    return bytes(
        [0x80 | (0x10 if sbit else 0), 0xE0, 0x80 | ((pid >> 8) & 0x7F),
         pid & 0xFF, tl0 & 0xFF, ((tid & 0x3) << 6) | 0x20,
         0x00 if keyframe else 0x01]
    )


def _build_traffic_lib(ssrcs, tick_ms: int, n_ticks: int, video_kbps: float):
    """A cyclable library of per-tick publisher datagram batches.

    Each tick entry: a writable blob + per-datagram (offset, length,
    stream index, built-in SN/TS). On every reuse cycle the publisher
    patches SN/TS in place (vectorized big-endian writes) so streams stay
    continuous forever — SNs advance by each stream's per-cycle packet
    count, TS by the library's wall span.
    """
    v_pps = video_kbps * 125.0 / 1200.0          # 1200-byte video packets
    kf_every = max(1, 200 // tick_ms)            # keyframe each ~200 ms
    a_every = max(1, 20 // tick_ms)              # Opus: one packet / 20 ms
    sn_next = {i: 0 for i in range(len(ssrcs))}
    lib = []
    for tick in range(n_ticks):
        dgrams, sidx, sns, tss = [], [], [], []
        for i, (r, t, is_video, ssrc) in enumerate(ssrcs):
            if is_video:
                n = int((tick + 1) * v_pps * tick_ms / 1000.0) - int(
                    tick * v_pps * tick_ms / 1000.0
                )
                ts = (tick * 90 * tick_ms) & 0xFFFFFFFF
            else:
                n = 1 if tick % a_every == 0 else 0
                ts = (tick * 48 * tick_ms) & 0xFFFFFFFF
            for k in range(n):
                sn = sn_next[i]
                sn_next[i] += 1
                hdr = bytearray(12)
                hdr[0] = 0x80
                hdr[1] = (0x80 if k == n - 1 else 0) | (96 if is_video else 111)
                hdr[2:4] = (sn & 0xFFFF).to_bytes(2, "big")
                hdr[4:8] = ts.to_bytes(4, "big")
                hdr[8:12] = ssrc.to_bytes(4, "big")
                if is_video:
                    payload = _vp8_descriptor(
                        tick & 0x7FFF, tick & 0xFF, k % 2, sbit=k == 0,
                        keyframe=tick % kf_every == 0 and k == 0,
                    ) + bytes(1100)
                else:
                    payload = bytes(80)
                dgrams.append(bytes(hdr) + payload)
                sidx.append(i)
                sns.append(sn)
                tss.append(ts)
        blob, offs, lens = _stage_frames(dgrams)
        lib.append({
            "blob": blob.copy(),
            "offs": offs, "lens": lens,
            "sidx": np.array(sidx, np.int64),
            "sn0": np.array(sns, np.int64),
            "ts0": np.array(tss, np.int64),
        })
    sn_per_cycle = np.array([sn_next[i] for i in range(len(ssrcs))], np.int64)
    ts_per_cycle = np.array(
        [n_ticks * (90 if v else 48) * tick_ms for (_, _, v, _) in ssrcs],
        np.int64,
    )
    return lib, sn_per_cycle, ts_per_cycle


def _stage_frames(frames: list) -> tuple:
    """frames → (blob, offs int64, lens int32) in native send_raw layout."""
    lens = np.array([len(f) for f in frames], np.int32)
    offs = np.zeros(len(frames), np.int64)
    if len(frames) > 1:
        np.cumsum(lens[:-1].astype(np.int64), out=offs[1:])
    return np.frombuffer(b"".join(frames), np.uint8), offs, lens


def _patch_tick(entry, cycle: int, sn_pc, ts_pc) -> None:
    """Advance one library tick's SN/TS fields for reuse cycle `cycle`."""
    if cycle == 0 or not len(entry["offs"]):
        return
    blob, offs = entry["blob"], entry["offs"]
    s = entry["sidx"]
    sn = (entry["sn0"] + cycle * sn_pc[s]) & 0xFFFF
    ts = (entry["ts0"] + cycle * ts_pc[s]) & 0xFFFFFFFF
    blob[offs + 2] = sn >> 8
    blob[offs + 3] = sn & 0xFF
    blob[offs + 4] = ts >> 24
    blob[offs + 5] = (ts >> 16) & 0xFF
    blob[offs + 6] = (ts >> 8) & 0xFF
    blob[offs + 7] = ts & 0xFF


async def wire_bench(
    dims,
    tick_ms: int = 5,
    duration_s: float = 8.0,
    warm_ticks: int = 30,
    video_tracks: int = 4,
    audio_tracks: int = 4,
    video_kbps: float = 3000.0,
    ack_ms: float = 25.0,
    n_slices: int = 4,
    warm_timeout_s: float = 120.0,
    low_latency: bool = False,
    egress_shards: int = 0,
    express_max_subs: int = 0,
) -> dict:
    """Real-time serving-loop measurement (see module-section comment).

    Everything reported here is measured wall-clock on this process's real
    sockets — publisher → kernel → recvmmsg → parse/stage → device tick →
    egress build/seal → kernel send — with tick-queueing wait included via
    the ForwardLatencyProbe stamps. On a tunneled dev chip the device
    round trip dominates; `tunnel_rtt_ms` is measured alongside so the
    floor is visible in the record.
    """
    import socket as _socket

    import jax.numpy as jnp

    from livekit_server_tpu.runtime import PlaneRuntime
    from livekit_server_tpu.runtime.crypto import (
        MediaCryptoClient,
        MediaCryptoRegistry,
    )
    from livekit_server_tpu.native import egress as native_egress
    from livekit_server_tpu.runtime.udp import (
        build_remb,
        build_twcc_feedback,
        start_udp_transport,
    )

    # Device round-trip floor of this rig (dispatch + fetch of a trivial
    # computation) — the part of the measured latency no host design can
    # remove on a tunneled chip. One throwaway call pays the compile.
    int(jnp.zeros((), jnp.int32) + 1)
    rtts = []
    for _ in range(3):
        t0 = time.perf_counter()
        int(jnp.zeros((), jnp.int32) + 1)
        rtts.append(time.perf_counter() - t0)
    tunnel_rtt_ms = round(float(np.median(rtts)) * 1000.0, 2)

    runtime = PlaneRuntime(dims, tick_ms=tick_ms, low_latency=low_latency,
                           egress_shards=egress_shards,
                           express_max_subs=express_max_subs,
                           express_max_rooms=dims.rooms)
    reg = MediaCryptoRegistry()
    udp = await start_udp_transport(
        runtime.ingest, host="127.0.0.1", port=0, crypto=reg
    )
    # Production egress path: the sharded plane orchestrator (room-aligned
    # shards + canonical-group staging), same wiring as service/server.py.
    udp.attach_egress_plane(runtime.egress_plane)
    # Flight-recorder attribution: sampled arrival→wire stage split
    # (same wiring as service/server.py start()).
    udp.wire_stages = runtime.wire_stages
    if runtime.express is not None:
        # Two-tier latency plane: eligible rooms forward on arrival.
        udp.attach_express(runtime.express)
    srv_addr = udp.transport.get_extra_info("sockname")
    srv_ip, srv_port = 0x7F000001, srv_addr[1]

    def mk_sock():
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        s.bind(("127.0.0.1", 0))
        s.setblocking(False)
        s.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, 4 << 20)
        s.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, 4 << 20)
        return s

    pub_sock = mk_sock()    # all publisher streams
    ack_sock = mk_sock()    # sealed cohort sink + TWCC feedback source
    sink_sock = mk_sock()   # legacy cohort sink (never read) + REMB source

    nv = min(video_tracks, dims.tracks)
    used = min(nv + audio_tracks, dims.tracks)
    ssrcs = []
    acked = []   # (room, sub, session, client, media_ssrc)
    remb_subs = []
    for r in range(dims.rooms):
        for t in range(used):
            is_video = t < nv
            ssrc = udp.assign_ssrc(r, t, is_video)
            runtime.set_track(r, t, published=True, is_video=is_video)
            ssrcs.append((r, t, is_video, ssrc))
        for s in range(dims.subs):
            for t in range(used):
                runtime.set_subscription(r, t, s, subscribed=True)
            if s == 0:
                # Modern client: sealed egress (TWCC counters on the wire).
                sess = reg.mint()
                udp.bind_sub_session(r, s, sess)
                udp.register_subscriber(r, s, ack_sock.getsockname())
                client = MediaCryptoClient(sess.key_id, sess.key)
                acked.append([r, s, sess, client, 0])
            else:
                udp.register_subscriber(r, s, sink_sock.getsockname())
                remb_subs.append((r, s))
    # The sealed cohort announces itself (client_active latch → fb_enabled);
    # a tiny sealed RTCP RR is the hello real SDK clients send first.
    hello = bytes([0x80, 201, 0, 1]) + (0x1234).to_bytes(4, "big")
    for ent in acked:
        ack_sock.sendto(ent[3].seal(hello), ("127.0.0.1", srv_port))
    await asyncio.sleep(0.1)
    for ent in acked:
        ent[4] = udp.subscriber_ssrc(ent[0], ent[1], 0)
    kid_to_ent = {ent[2].key_id: ent for ent in acked}

    # Publisher library: 1 s of traffic, cycled with in-place SN/TS patch.
    lib, sn_pc, ts_pc = _build_traffic_lib(
        ssrcs, tick_ms, max(1, 1000 // tick_ms), video_kbps
    )
    for e in lib:
        n = len(e["offs"])
        e["ips"] = np.full(n, srv_ip, np.uint32)
        e["ports"] = np.full(n, srv_port, np.uint16)
        # Slice bounds for sub-tick arrival spreading.
        e["cuts"] = np.linspace(0, n, n_slices + 1).astype(np.int64)

    # REMB blob (legacy cohort estimate channel): rebuilt never — the
    # frames are stateless; one send_raw per interval from the sink sock.
    est_bps = 1.25 * 1000.0 * (video_tracks * video_kbps + audio_tracks * 64.0)
    remb_frames = [
        build_remb(0x42, est_bps, [udp.subscriber_ssrc(r, s, 0)])
        for (r, s) in remb_subs
    ]
    remb_blob, remb_offs, remb_lens = _stage_frames(remb_frames)
    remb_ips = np.full(len(remb_frames), srv_ip, np.uint32)
    remb_ports = np.full(len(remb_frames), srv_port, np.uint16)

    # Instrument device wall time (per in-loop call) + per-tick host work.
    dev_s = [0.0]
    orig_step = runtime._device_step

    def timed_step(inp):
        t0 = time.perf_counter()
        out = orig_step(inp)
        dev_s[0] += time.perf_counter() - t0
        return out

    runtime._device_step = timed_step
    tick_acc = [0, 0.0]  # ticks seen, Σ tick_s
    # Late-tick CAUSE breakdown: for each deadline miss, which pipeline
    # term dominated the tick — the wake-edge overshoot, staging, the
    # device step, or fan-out. Classified from the tick record _complete
    # just appended (recent_ticks[-1] is this tick's).
    late_cause = {"edge": 0, "stage": 0, "device": 0, "fanout": 0}

    def on_tick(res):
        udp.send_egress_batch(res.egress_batch, pacer_allowed=res.pacer_allowed)
        tick_acc[0] += 1
        tick_acc[1] += res.tick_s
        rec = runtime.recent_ticks[-1] if runtime.recent_ticks else None
        if rec and rec.get("late"):
            parts = {
                "edge": rec.get("edge_overshoot_us", 0.0) / 1000.0,
                "stage": rec.get("stage_ms", 0.0),
                "device": rec.get("device_ms", 0.0),
                "fanout": rec.get("fanout_ms", 0.0),
            }
            late_cause[max(parts, key=parts.get)] += 1

    runtime.on_tick(on_tick)

    stop = asyncio.Event()
    import threading

    stop_thr = threading.Event()
    pub_stats = {"sent": 0, "skipped_ticks": 0}

    def publisher_thread():
        """Real-time load generator in its own OS thread: the asyncio
        loop's long synchronous spans (rx callbacks, staging, fan-out)
        would starve a task-based pacer. Behind-schedule slices are sent
        in a burst; if the generator falls >0.5 s behind (overloaded
        rig), whole ticks are skipped and counted rather than building an
        unbounded backlog."""
        period = tick_ms / 1000.0
        slice_p = period / n_slices
        i, cycle = 0, 0
        next_at = time.perf_counter() + slice_p
        pf = pub_sock.fileno()
        while not stop_thr.is_set():
            behind = time.perf_counter() - next_at
            if behind > 0.5:
                n_skip = int(behind / period)
                pub_stats["skipped_ticks"] += n_skip
                for _ in range(n_skip):
                    next_at += period
                    i += 1
                    if i == len(lib):
                        i, cycle = 0, cycle + 1
                continue
            e = lib[i]
            _patch_tick(e, cycle, sn_pc, ts_pc)
            cuts = e["cuts"]
            for sl in range(n_slices):
                lag = next_at - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                lo, hi = int(cuts[sl]), int(cuts[sl + 1])
                if hi > lo:
                    pub_stats["sent"] += native_egress.send_raw(
                        pf, e["blob"], e["offs"][lo:hi], e["lens"][lo:hi],
                        e["ips"][lo:hi], e["ports"][lo:hi],
                    )
                next_at += slice_p
            i += 1
            if i == len(lib):
                i, cycle = 0, cycle + 1

    async def acker():
        """Sealed-cohort reader: drain egress, ack counters as RTPFB
        fmt-15 through the server's real RTCP path."""
        MAXN, MAXD = 2048, 2048
        scratch = np.zeros(MAXN * MAXD, np.uint8)
        offs = np.zeros(MAXN, np.int32)
        lens = np.zeros(MAXN, np.int32)
        ips = np.zeros(MAXN, np.uint32)
        ports = np.zeros(MAXN, np.uint16)
        af = ack_sock.fileno()
        while not stop.is_set():
            await asyncio.sleep(ack_ms / 1000.0)
            frames = []
            while True:
                nn = native_egress.rx_batch(af, scratch, offs, lens, ips, ports, MAXD)
                if nn <= 0:
                    break
                now_us = int(time.perf_counter() * 1e6)
                o = offs[:nn].astype(np.int64)
                sealed = scratch[o] == 0x01
                if sealed.any():
                    so = o[sealed]
                    kid = (
                        (scratch[so + 1].astype(np.int64) << 24)
                        | (scratch[so + 2].astype(np.int64) << 16)
                        | (scratch[so + 3].astype(np.int64) << 8)
                        | scratch[so + 4]
                    )
                    ctr = np.zeros(len(so), np.int64)
                    for b in range(8):
                        ctr = (ctr << 8) | scratch[so + 6 + b].astype(np.int64)
                    for k in np.unique(kid):
                        ent = kid_to_ent.get(int(k))
                        if ent is None:
                            continue
                        sel = np.sort(ctr[kid == k])
                        # Counters in one feedback frame must span < 2^16
                        # (ctr_off is u16): a kernel-drop gap can exceed
                        # that — split at the discontinuity.
                        lo = 0
                        while lo < len(sel):
                            hi = int(np.searchsorted(sel, sel[lo] + 0xFFFF))
                            frames.append(build_twcc_feedback(
                                0x42, ent[4],
                                [(int(c), now_us) for c in sel[lo:hi]],
                            ))
                            lo = hi
                if nn < MAXN:
                    break
            if frames:
                fb_blob, fb_offs, fb_lens = _stage_frames(frames)
                native_egress.send_raw(
                    af, fb_blob, fb_offs, fb_lens,
                    np.full(len(frames), srv_ip, np.uint32),
                    np.full(len(frames), srv_port, np.uint16),
                )

    async def remb_pump():
        while not stop.is_set():
            native_egress.send_raw(
                sink_sock.fileno(), remb_blob, remb_offs, remb_lens,
                remb_ips, remb_ports,
            )
            await asyncio.sleep(0.2)

    task_errors: list[str] = []

    async def guarded(coro, name):
        """A helper task dying mid-window must surface in the record, not
        silently degrade the measurement."""
        try:
            await coro
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            task_errors.append(f"{name}: {type(e).__name__}: {e}")

    tasks = [
        asyncio.ensure_future(guarded(acker(), "acker")),
        asyncio.ensure_future(guarded(remb_pump(), "remb")),
    ]
    pub_thr = threading.Thread(target=publisher_thread, daemon=True)
    pub_thr.start()
    try:
        runtime.start()

        # Warm-up: first ticks pay jit compile; wait for steady state.
        t0 = time.perf_counter()
        while (
            runtime.stats["ticks"] < warm_ticks
            and time.perf_counter() - t0 < warm_timeout_s
        ):
            await asyncio.sleep(0.05)

        # Close the recompile watchdog's warmup window with the warm
        # ticks: compiles during the measurement window below are
        # steady-state retraces (reported in the summary; should be 0).
        runtime.mark_warm()
        # Measurement window: reset every counter the report reads.
        udp.fwd_latency.reset()
        udp.fwd_latency_express.reset()
        if runtime.wire_stages is not None:
            # Same window discipline as the probes: compile/warmup-era
            # samples (a 2+ s first device step) would poison the stage
            # percentiles.
            runtime.wire_stages.reset()
        dev_s[0] = 0.0
        tick_acc[0], tick_acc[1] = 0, 0.0
        for key in late_cause:
            late_cause[key] = 0
        base = {
            "ticks": runtime.stats["ticks"],
            "late": runtime.stats["late_ticks"],
            "rx": udp.stats["rx"],
            "tx": udp.stats["tx"],
            "twcc": udp.stats.get("twcc_rx", 0),
            "dropped": runtime.ingest.dropped,
            "fwd": runtime.stats["fwd_packets"],
            # Per-stage pipeline accounting (three-stage tick loop).
            "stage_s": runtime.stats.get("stage_s", 0.0),
            "device_s": runtime.stats.get("device_s", 0.0),
            "fanout_s": runtime.stats.get("fanout_s", 0.0),
            "stalls": runtime.stats.get("pipeline_stalls", 0),
        }
        t_meas = time.perf_counter()
        await asyncio.sleep(duration_s)
        wall = time.perf_counter() - t_meas
        probe = udp.fwd_latency.summary()
        probe_ex = udp.fwd_latency_express.summary()
        ticks = runtime.stats["ticks"] - base["ticks"]
        tx = udp.stats["tx"] - base["tx"]
        host_busy_s = max(tick_acc[1] - dev_s[0], 1e-9)
    finally:
        # The publisher floods ~280k pps: it MUST die even when the
        # measurement throws, or every later bench section is corrupted.
        stop.set()
        stop_thr.set()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        pub_thr.join(timeout=2.0)
        await runtime.stop()
        runtime._device_step = orig_step
        udp.transport.close()
        pub_sock.close()
        ack_sock.close()
        sink_sock.close()

    rx = udp.stats["rx"] - base["rx"]
    dropped = runtime.ingest.dropped - base["dropped"]
    n_ticks = max(ticks, 1)

    def stage_ms(key: str) -> float:
        """Measurement-window per-tick mean of one pipeline stage."""
        return round(
            (runtime.stats.get(key, 0.0) - base[key]) / n_ticks * 1000.0, 3
        )

    out = {
        "tick_ms": tick_ms,
        "p50_wire_ms": probe["p50_ms"],
        "p99_wire_ms": probe["p99_ms"],
        "p999_wire_ms": probe["p999_ms"],
        "mean_wire_ms": probe["mean_ms"],
        "max_wire_ms": probe["max_ms"],
        "lat_samples": probe["n"],
        "late_cause": dict(late_cause),
        "sleep_bias_us": round(max(runtime._sleep_bias, 0.0) * 1e6, 1),
        "tunnel_rtt_ms": tunnel_rtt_ms,
        "ticks": ticks,
        "achieved_tick_hz": round(ticks / wall, 1) if wall else 0.0,
        "late_ticks": runtime.stats["late_ticks"] - base["late"],
        "wire_in_pps": round(rx / wall, 1),
        "wire_out_pps": round(tx / wall, 1),
        "host_ms_per_tick": round(host_busy_s / max(ticks, 1) * 1000.0, 3),
        "dev_ms_per_tick": round(dev_s[0] / max(ticks, 1) * 1000.0, 3),
        # Per-stage pipeline split (runtime.stats deltas): the overlap win
        # is measured per stage, not inferred from host_ms_per_tick.
        "stage_ms_per_tick": stage_ms("stage_s"),
        "device_ms_per_tick": stage_ms("device_s"),
        "fanout_ms_per_tick": stage_ms("fanout_s"),
        "pipeline_depth": 0 if runtime.low_latency else 1,
        "pipeline_stalls": runtime.stats.get("pipeline_stalls", 0) - base["stalls"],
        "host_egress_pps": round(tx / host_busy_s, 1) if tx else 0.0,
        # Sharded-plane view of the same window: EMA of entries over the
        # per-tick critical-path (max-shard) send time, and the share of
        # entries served from a staged canonical instead of a full build.
        "plane_pps": runtime.egress_plane.observe()["host_egress_pps"],
        "plane_shards": runtime.egress_plane.shards,
        "grouped_pct": round(
            100.0 * runtime.egress_plane.stats["grouped_entries"]
            / max(runtime.egress_plane.stats["entries"], 1), 1
        ),
        "twcc_acks": udp.stats.get("twcc_rx", 0) - base["twcc"],
        "ingest_dropped_pct": round(100.0 * dropped / max(rx, 1), 2),
        "fwd_packets": runtime.stats["fwd_packets"] - base["fwd"],
        "pub_skipped_ticks": pub_stats["skipped_ticks"],
        # Sampled per-stage wire-latency decomposition (trace.py
        # LatencyAttribution): where the batched tier's arrival→wire
        # time actually goes — staging wait vs device step vs egress.
        "stages": (runtime.wire_stages.summary()
                   if runtime.wire_stages is not None else {}),
        # Recompile watchdog over the measurement window: >0 means the
        # steady-state tick path retraced mid-run.
        "xla_compiles_post_warmup": runtime.compile_ledger.post_warmup,
        "xla_warmup_compile_ms": round(runtime.compile_ledger.warmup_ms, 1),
        **({"task_errors": task_errors} if task_errors else {}),
    }
    trace_out = os.environ.get("BENCH_TRACE_OUT")
    if trace_out and runtime.trace is not None:
        # Perfetto-loadable dump of the tick-span ring for this wire run
        # (same format as /debug/trace; validated by tools/trace).
        from livekit_server_tpu.telemetry import trace_export

        with open(trace_out, "w", encoding="utf-8") as fh:
            fh.write(trace_export.export_json(
                runtime.trace.snapshot(), tick_ms
            ))
    if runtime.express is not None:
        # Express-tier wire latency (arrival-driven sends; no tick-queue
        # wait) beside the batched tier's, plus the lane's own counters —
        # the two-tier split IS the tentpole measurement.
        out.update({
            "p50_wire_express_ms": probe_ex["p50_ms"],
            "p90_wire_express_ms": probe_ex["p90_ms"],
            "p99_wire_express_ms": probe_ex["p99_ms"],
            "p999_wire_express_ms": probe_ex["p999_ms"],
            "express_samples": probe_ex["n"],
            "express": runtime.express.debug(),
        })
    return out


# -- main -------------------------------------------------------------------

def _setup_compile_cache() -> None:
    """Persistent XLA compile cache keyed by the env fingerprint (AOT
    entries embed machine-tuning flags; a mismatched load can abort —
    see tests/conftest.py)."""
    import hashlib

    import jax

    fp = hashlib.md5(
        (
            os.environ.get("XLA_FLAGS", "")
            + "|" + os.environ.get("JAX_PLATFORMS", "")
            + "|" + str(jax.config.jax_platforms)
            + "|" + jax.__version__
        ).encode()
    ).hexdigest()[:10]
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "JAX_COMPILATION_CACHE_DIR", f"/tmp/jax_cache_livekit_tpu_{fp}"
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _run_wire(result_key: str, dims, tick_ms: int, duration_s: float,
              **kw) -> dict | None:
    """One wire_bench run into RESULT[result_key]; errors are recorded,
    never raised (a wire failure must not take down earlier numbers)."""
    try:
        wire = asyncio.run(wire_bench(dims, tick_ms=tick_ms,
                                      duration_s=duration_s, **kw))
        RESULT[result_key] = wire
        return wire
    except Exception as e:  # noqa: BLE001
        RESULT[result_key + "_error"] = f"{type(e).__name__}: {e}"
        return None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rooms", type=int, default=128)
    ap.add_argument("--tracks", type=int, default=8)
    ap.add_argument("--pkts", type=int, default=16)
    ap.add_argument("--subs", type=int, default=16)
    ap.add_argument("--ticks", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--budget", type=float, default=None,
                    help="total seconds (default: BENCH_BUDGET_S env or 480)")
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    ap.add_argument("--quick", action="store_true",
                    help="primary metric only (skip ladder/host/mem)")
    ap.add_argument("--wire-only", action="store_true",
                    help="run only the real-time wire bench; print its JSON")
    ap.add_argument("--wire-seconds", type=float, default=8.0)
    ap.add_argument("--wire-tick-ms", type=str, default="5",
                    help="tick_ms for the wire bench; comma list runs "
                         "multiple variants (--wire-only mode)")
    ap.add_argument("--wire-rooms", type=int, default=32)
    ap.add_argument("--wire-kbps", type=float, default=3000.0)
    ap.add_argument("--wire-low-latency", action="store_true",
                    help="complete egress in-tick (PlaneRuntime low_latency)")
    args = ap.parse_args()
    if args.budget is not None:
        _BUDGET[0] = args.budget

    signal.signal(signal.SIGTERM, _on_kill)
    signal.signal(signal.SIGINT, _on_kill)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    _setup_compile_cache()

    from livekit_server_tpu.models import plane, synth

    # Variant specs: "5,2,2e" — a trailing 'e' runs that tick rate with
    # the express lane enabled (express_max_subs = the wire shape's subs,
    # so every room is eligible).
    wire_specs = [s.strip() for s in str(args.wire_tick_ms).split(",")]
    wire_ticks = [int(s.rstrip("e")) for s in wire_specs]

    if args.wire_only:
        # Twin-subprocess mode: all requested tick variants in ONE process
        # (tick_ms is a traced input, so extra variants cost no recompile).
        for spec, t in zip(wire_specs, wire_ticks):
            key = "wire" if spec == wire_specs[0] else f"wire_tick{spec}"
            _SECTION[0] = key
            dims_w = plane.PlaneDims(args.wire_rooms, 8, 8, 6)
            _run_wire(key, dims_w, t,
                      args.wire_seconds, video_kbps=args.wire_kbps,
                      low_latency=args.wire_low_latency,
                      express_max_subs=(dims_w.subs if spec.endswith("e")
                                        else 0))
            emit()
        return

    # -- primary metric (always; it IS the scoreboard line) ---------------
    _SECTION[0] = "primary"
    t_sec = time.perf_counter()
    dims = plane.PlaneDims(args.rooms, args.tracks, args.pkts, args.subs)
    # Dense, realistic load: 4×3 Mbps simulcast video + 4 Opus tracks per
    # room at a 20 ms tick ≈ 6-7 video pkts/track/tick.
    spec = synth.TrafficSpec(video_tracks=4, audio_tracks=4, tick_ms=20,
                             video_kbps=3000)
    RESULT.update({
        "metric": "sfu_pkt_sub_writes_per_sec_per_chip",
        "value": 0.0,
        "unit": "writes/s",
        "vs_baseline": 0.0,
        "counted": "forwarded (pkt × subscriber) writes; drops excluded",
    })
    emit()  # a diagnosable record exists from the first seconds on
    try:
        primary = device_bench(dims, spec, args.ticks, args.warmup)
        RESULT.update({
            "value": primary["fwd_writes_per_s"],
            "vs_baseline": round(
                primary["fwd_writes_per_s"] / BASELINE_WRITES_PER_SEC, 2
            ),
            "evaluated_per_s": primary["evaluated_per_s"],
            "device_tick_ms": primary["device_tick_ms"],
        })
    except Exception as e:  # noqa: BLE001 — the r4 lesson: a primary
        # crash must still leave a parseable record on stdout.
        RESULT["primary_error"] = f"{type(e).__name__}: {e}"
    section_done("primary", t_sec)
    if args.quick:
        return

    # -- sharded egress plane microbench (host packet walk, no device) ----
    # The number the egress plane exists to move: datagrams/s through the
    # native sharded assemble(+seal) walk on a wire-shaped batch (32 rooms
    # × 6 subs × 4 video tracks × 7 pkts @ 1100 B ≈ the wire bench's video
    # load per tick). Clear vs sealed split makes the AES share visible;
    # room-aligned shards share no state, so multi-core nodes scale the
    # clear/sealed numbers by core count.
    if section_ok("egress_plane", 20):
        t_sec = time.perf_counter()
        try:
            from livekit_server_tpu.runtime.egress_plane import (
                EgressPlane,
                bench_plane,
            )

            ep = EgressPlane(0)  # all local cores
            shape = dict(n_rooms=32, subs_per_room=6, tracks=4, pkts=7)
            # Warm pass (discarded): pool spin-up + page faults on the
            # scratch/out buffers land here, not in the measurement —
            # this section runs right after the JAX-heavy primary and
            # starts cache-cold.
            bench_plane(ep, payload_len=1100, sealed=False, seconds=0.5,
                        **shape)
            clear = max(
                (bench_plane(ep, payload_len=1100, sealed=False,
                             seconds=2.0, **shape) for _ in range(2)),
                key=lambda r: r.get("pps", 0.0),
            )
            sealed = bench_plane(ep, payload_len=1100, sealed=True,
                                 seconds=2.0, **shape)
            audio = bench_plane(ep, payload_len=160, sealed=True,
                                seconds=1.5, **shape)
            RESULT["egress_plane"] = {
                "shards": ep.shards,
                "pps_clear_build": clear.get("pps", 0.0),
                "pps_sealed_build": sealed.get("pps", 0.0),
                "pps_sealed_160B": audio.get("pps", 0.0),
                "grouped_pct": sealed.get("grouped_pct", 0.0),
                "entries_per_call": sealed.get("entries_per_call", 0),
            }
            # Shard-scaling curve: N shards on N cores, sealed walk. On
            # a 1-CPU rig this is a single point (flagged); a multi-core
            # node records the actual knee instead of the "multiply by
            # cores" assumption (BASELINE.md).
            if (os.cpu_count() or 1) > 1 and section_ok("plane_scaling", 10):
                from livekit_server_tpu.runtime.egress_plane import (
                    bench_plane_scaling,
                )

                RESULT["egress_plane"]["scaling"] = bench_plane_scaling(
                    payload_len=1100, sealed=True,
                    seconds_per_point=1.0, **shape,
                )
            # Scoreboard line: host egress packet walk on the wire shape
            # (clear assembly; the sealed and on-wire variants are beside
            # it and in the wire sections — see BASELINE.md round 6).
            RESULT["host_egress_pps"] = clear.get("pps", 0.0)
        except Exception as e:  # noqa: BLE001
            RESULT["egress_plane_error"] = f"{type(e).__name__}: {e}"
        section_done("egress_plane", t_sec)

    # Section order is by information value under the budget: the CPU-twin
    # latency answer and the two headline device shapes (cfg4, north-star)
    # come before the tunnel-floor-bound TPU wire run, the 128-room wire
    # variant, the tiny ladder configs, and the mix kernel — so a tight
    # deadline starves trivia, not headlines.

    # -- CPU-twin wire bench (locally-attached analog) --------------------
    # The TPU here is behind a ~100 ms tunnel, so its wire numbers are
    # tunnel-floor-bound; the identical host path + an XLA:CPU device
    # in a subprocess shows what a locally-attached chip does (the TPU
    # device tick is faster than CPU's, so this bounds it from above).
    # Runs tick_ms=5 and tick_ms=2 variants in one subprocess.
    if not args.cpu and section_ok("wire_local", 100):
        import subprocess

        t_sec = time.perf_counter()

        def _absorb_twin(stdout: str) -> None:
            twin = absorb_twin_json(stdout)
            RESULT["wire_local"] = twin.get("wire")
            RESULT["wire_local_tick2"] = twin.get("wire_tick2")
            RESULT["wire_local_express"] = twin.get("wire_tick2e")
            RESULT["wire_local_express_tick5"] = twin.get("wire_tick5e")
            if RESULT["wire_local"]:
                RESULT["p99_wire_local_ms"] = RESULT["wire_local"]["p99_wire_ms"]
            # The scoreboard latency number is the express tier's when an
            # express variant ran and carried samples: that is the serving
            # configuration an interactive room actually gets. Express
            # latency is arrival-driven (tick-independent), so the tick
            # rate is an operator throughput knob — record the best tier
            # and which variant produced it.
            express_runs = [
                (spec, twin.get(f"wire_tick{spec}") or {})
                for spec in ("2e", "5e")
            ]
            express_runs = [
                (spec, w) for spec, w in express_runs
                if w.get("express_samples")
            ]
            if express_runs:
                spec, best = min(
                    express_runs,
                    key=lambda sw: sw[1]["p99_wire_express_ms"],
                )
                RESULT["p99_wire_local_batched_ms"] = (
                    RESULT.get("p99_wire_local_ms")
                )
                RESULT["p99_wire_local_ms"] = best["p99_wire_express_ms"]
                RESULT["p99_wire_local_express_variant"] = f"tick{spec}"

        try:
            twin_budget = min(_remaining() - 20, 200)
            # 8 rooms × 1.5 Mbps: the largest load whose XLA:CPU device
            # step (~2.8 ms) leaves the 5 ms tick any headroom — at 32
            # rooms the CPU device step alone is ~5.4 ms and the twin
            # measures queue collapse, not the serving loop. The TPU
            # device tick at the full 32-room wire shape is measured
            # separately (wire_shape_device_tick_ms) for the
            # locally-attached projection.
            # Pipelined loop (depth 1), same as the TPU wire section: the
            # three-stage overlap is the serving configuration the tick
            # budget is engineered for; --wire-low-latency remains a
            # manual knob for measuring the depth-0 latency trade.
            cp = subprocess.run(
                [sys.executable, __file__, "--wire-only", "--cpu",
                 "--wire-seconds", str(args.wire_seconds),
                 "--wire-tick-ms", f"{wire_ticks[0]},2,2e,5e",
                 "--wire-rooms", "8", "--wire-kbps", "1500"],
                capture_output=True, text=True, timeout=max(twin_budget, 45),
            )
            _absorb_twin(cp.stdout)
        except subprocess.TimeoutExpired as e:
            # The child emits incrementally too: salvage what it printed
            # before the timeout killed it.
            RESULT["wire_local_error"] = "TimeoutExpired"
            try:
                out = e.stdout
                _absorb_twin(out.decode() if isinstance(out, bytes) else out)
            except Exception:  # noqa: BLE001
                pass
        except Exception as e:  # noqa: BLE001
            RESULT["wire_local_error"] = f"{type(e).__name__}: {e}"
        section_done("wire_local", t_sec)

    # -- fleet traffic twin (capacity/SLO envelope) -----------------------
    # Deterministic production-shaped load (runtime/traffic_twin): diurnal
    # churn + flash crowd + rolling drain replayed across a 2-node bus,
    # swept over >= 4 offered-load multipliers. Runs as an XLA:CPU
    # subprocess — the twin drives virtual time through the full
    # admission → governor → plane → egress stack, so it measures
    # robustness SLOs (admission rate, audio continuity, rung residency,
    # recovery ticks), not device speed. The child emits a partial curve
    # after every load step, so a timeout kill salvages the completed
    # steps via the same last-line-JSON contract as the wire twin.
    if section_ok("fleet_twin", 90):
        import subprocess

        t_sec = time.perf_counter()
        try:
            twin_budget = max(min(_remaining() - 20, 240), 60)
            cp = subprocess.run(
                [sys.executable, "-m",
                 "livekit_server_tpu.runtime.traffic_twin",
                 "--seed", "20", "--ticks", "60", "--nodes", "2",
                 "--loads", "0.5,1.0,2.0,4.0"],
                capture_output=True, text=True, timeout=twin_budget,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            RESULT["fleet_twin"] = absorb_twin_json(cp.stdout)
        except subprocess.TimeoutExpired as e:
            RESULT["fleet_twin_error"] = "TimeoutExpired"
            try:
                out = e.stdout
                RESULT["fleet_twin"] = absorb_twin_json(
                    out.decode() if isinstance(out, bytes) else out)
            except Exception:  # noqa: BLE001
                pass
        except Exception as e:  # noqa: BLE001
            RESULT["fleet_twin_error"] = f"{type(e).__name__}: {e}"
        section_done("fleet_twin", t_sec)

    # -- BASELINE.md ladder (device throughput) ---------------------------
    ladder = {
        "cfg1_1room_2p_audio": (
            plane.PlaneDims(1, 2, 8, 2),
            synth.TrafficSpec(video_tracks=0, audio_tracks=2, tick_ms=20),
            25,
        ),
        "cfg2_1room_50p_audio": (
            plane.PlaneDims(1, 50, 8, 50),
            synth.TrafficSpec(video_tracks=0, audio_tracks=50, tick_ms=20),
            25,
        ),
        "cfg3_1room_25p_vp8_simulcast": (
            plane.PlaneDims(1, 25, 16, 25),
            synth.TrafficSpec(video_tracks=25, audio_tracks=0, tick_ms=20,
                              video_kbps=3000),
            25,
        ),
        "cfg4_1krooms_10p_mixed_svc": (
            plane.PlaneDims(1024, 10, 8, 10),
            synth.TrafficSpec(video_tracks=2, audio_tracks=8, tick_ms=20,
                              video_kbps=1500, svc=True),
            40,
        ),
    }
    configs = RESULT.setdefault("configs", {})

    def run_ladder(name):
        d, s, est = ladder[name]
        if not section_ok(name, est):
            return
        t_sec = time.perf_counter()
        try:
            r = device_bench(d, s, ticks=15, warmup=3)
            configs[name] = r["fwd_writes_per_s"]
            configs[name + "_tick_ms"] = r["device_tick_ms"]
            if r.get("dispatch_bound"):
                configs[name + "_dispatch_bound"] = True
        except Exception as e:  # noqa: BLE001
            configs[name] = f"error: {type(e).__name__}"
        section_done(name, t_sec)

    # cfg4 first: it is the ladder's load-bearing rung.
    run_ladder("cfg4_1krooms_10p_mixed_svc")
    RESULT["cfg5_note"] = "multi-node sharding validated by dryrun_multichip"

    # -- device tick at the WIRE shape (locally-attached projection) ------
    # The real-chip compute cost of the wire bench's 32-room shape: with
    # the host loop's measured ms/tick from wire_local, this is the term
    # a locally-attached chip substitutes for the CPU twin's ~5 ms step.
    if section_ok("wire_shape_tick", 30):
        t_sec = time.perf_counter()
        try:
            r = device_bench(
                plane.PlaneDims(32, 8, 8, 6),
                synth.TrafficSpec(video_tracks=4, audio_tracks=4, tick_ms=5,
                                  video_kbps=3000),
                ticks=50, warmup=5,
            )
            RESULT["wire_shape_device_tick_ms"] = r["device_tick_ms"]
            if r.get("dispatch_bound"):
                RESULT["wire_shape_dispatch_bound"] = True
        except Exception as e:  # noqa: BLE001
            RESULT["wire_shape_error"] = f"{type(e).__name__}"
        section_done("wire_shape_tick", t_sec)

    # -- north-star tick: FULL 10k-rooms × 50-subs plane on ONE chip ------
    # (BASELINE target is 10k×50 on v5e-8; room-sharding divides by mesh
    # size, so single-chip-tick/8 estimates per-chip cost on the pod.)
    if section_ok("northstar", 80):
        t_sec = time.perf_counter()
        try:
            d = plane.PlaneDims(10240, 8, 16, 50)
            s = synth.TrafficSpec(video_tracks=2, audio_tracks=6, tick_ms=20,
                                  video_kbps=1500, svc=True)
            r = device_bench(d, s, ticks=5, warmup=1)
            RESULT["northstar_10240rooms_50subs_tick_ms"] = r["device_tick_ms"]
            RESULT["mem_1k_rooms_50subs_ok"] = True  # 10k×50 subsumes it
        except Exception as e:  # noqa: BLE001
            RESULT["northstar_error"] = f"{type(e).__name__}"
            # 10k failing says nothing about 1k×50 — measure the smaller
            # feasibility claim independently before reporting False.
            try:
                d1 = plane.PlaneDims(1024, 8, 16, 50)
                s1 = synth.TrafficSpec(video_tracks=2, audio_tracks=6,
                                       tick_ms=20)
                device_bench(d1, s1, ticks=2, warmup=1)
                RESULT["mem_1k_rooms_50subs_ok"] = True
            except Exception as e1:  # noqa: BLE001
                RESULT["mem_1k_rooms_50subs_ok"] = False
                RESULT["mem_error"] = f"{type(e1).__name__}"
        section_done("northstar", t_sec)

    # -- real-time wire bench on the TPU (tunnel-floor-bound here) --------
    # Shape within the kernel UDP path's capacity: 32 rooms × 6 subs
    # ≈ 280k wire pps (the dense primary shape over-subscribes loopback
    # ~10× and would measure socket queueing, not the server). On this rig
    # each tick's dispatch pays the ~100 ms tunnel RTT, so p99 here is the
    # tunnel's, not the server's — wire_local above is the honest analog;
    # this section records the floor and the host-side pps.
    if section_ok("wire", 75):
        t_sec = time.perf_counter()
        wire = _run_wire("wire", plane.PlaneDims(32, 8, 8, 6),
                         wire_ticks[0], args.wire_seconds)
        if wire:
            RESULT["p50_wire_ms"] = wire["p50_wire_ms"]
            RESULT["p99_wire_ms"] = wire["p99_wire_ms"]
            # End-to-end (tick-scheduled, socket-backed) egress rate; the
            # isolated packet-walk scoreboard lives in RESULT
            # ["host_egress_pps"] from the egress_plane section.
            RESULT["wire_host_egress_pps"] = wire["host_egress_pps"]
        section_done("wire", t_sec)

    # -- wire bench at 128-room scale -------------------------------------
    # Loopback's sender-inline delivery caps total wire bytes, so scale
    # ROOMS while trimming per-room load (2×500 kbps video + 4 audio × 4
    # subs ≈ 160k wire pps): exercises host ingest/egress + the probe at
    # cfg4-adjacent room/slot counts.
    if section_ok("wire_128rooms", 75):
        t_sec = time.perf_counter()
        wire_big = _run_wire(
            "wire_128rooms", plane.PlaneDims(128, 6, 8, 4),
            wire_ticks[0], args.wire_seconds,
            video_tracks=2, audio_tracks=4, video_kbps=500.0,
        )
        if wire_big:
            RESULT["p99_wire_128rooms_ms"] = wire_big["p99_wire_ms"]
        section_done("wire_128rooms", t_sec)

    # -- wire-shape ramp: rooms up until the serving loop breaks ----------
    # The per-node capacity claim measured, not extrapolated: run the wire
    # shape at increasing room counts until late ticks exceed 10% of the
    # window or ingest drops exceed 5% — the last clean rung is the "one
    # node serves N rooms of the wire config end-to-end" number
    # (BASELINE.md round 6). Short windows: each rung only has to clear
    # or trip the break thresholds, not produce publication latencies.
    if section_ok("wire_ramp", 120):
        t_sec = time.perf_counter()
        ramp_steps = []
        max_ok = 0
        tick_ramp = wire_ticks[0]
        rungs = [32, 48, 64, 96, 128]
        i = 0
        while i < len(rungs):
            rooms = rungs[i]
            if _remaining() < 35:
                RESULT.setdefault("skipped", {})["wire_ramp_tail"] = (
                    f"budget: stopped before {rooms} rooms"
                )
                break
            w = _run_wire(
                f"wire_ramp_{rooms}_t{tick_ramp}",
                plane.PlaneDims(rooms, 8, 8, 6),
                tick_ramp, min(args.wire_seconds, 4.0),
            )
            if w is None:
                break
            ticks_seen = max(w["ticks"], 1)
            late_pct = round(100.0 * w["late_ticks"] / ticks_seen, 1)
            step = {
                "rooms": rooms,
                "tick_ms": tick_ramp,
                "late_pct": late_pct,
                "ingest_dropped_pct": w["ingest_dropped_pct"],
                "wire_out_pps": w["wire_out_pps"],
                "host_egress_pps": w["host_egress_pps"],
            }
            ramp_steps.append(step)
            RESULT["wire_ramp"] = {
                "steps": ramp_steps, "max_rooms_ok": max_ok,
                "tick_ms": tick_ramp,
            }
            emit()
            if late_pct > 10.0 or w["ingest_dropped_pct"] > 5.0:
                # On a rig where the device step alone blows the 5 ms
                # deadline (CPU twin), the first rung breaks on tick
                # lateness before the egress/ingest planes are even
                # warm. Relax once to the 20 ms tick — same traffic,
                # deadline no longer device-bound — and re-measure the
                # same rung so the ramp reports the serving ceiling
                # rather than the device deadline.
                if max_ok == 0 and tick_ramp < 20:
                    tick_ramp = 20
                    continue
                break
            max_ok = rooms
            i += 1
        RESULT["wire_ramp"] = {
            "steps": ramp_steps, "max_rooms_ok": max_ok, "tick_ms": tick_ramp,
        }
        section_done("wire_ramp", t_sec)

    # -- ladder configs 1-3 (small shapes; device time is dispatch-bound
    # on this rig and flagged as such) ------------------------------------
    run_ladder("cfg1_1room_2p_audio")
    run_ladder("cfg2_1room_50p_audio")
    run_ladder("cfg3_1room_25p_vp8_simulcast")

    # -- paged capacity at a realistic room-size distribution -------------
    # The dense plane charges every room the worst-case [T, K, S] slab;
    # the paged plane charges the page grid the room actually covers.
    # Sample a production-shaped population (80% rooms ≤4 participants,
    # 15% ≤10, 5% the 50-participant north star; each participant = one
    # published track + one subscriber), drive a REAL RoomPager over a
    # fixed page pool, and report rooms-per-chip at EQUAL HBM both ways.
    # Pure host math — no device time.
    if section_ok("paged_capacity", 10):
        t_sec = time.perf_counter()
        try:
            from livekit_server_tpu.models import plane as plane_model
            from livekit_server_tpu.runtime.pager import RoomPager
            from livekit_server_tpu.runtime.slots import CapacityError

            T_MAX, S_MAX, TP, SP = 64, 64, 4, 8  # covers the 50-p room
            POOL = 1024

            def _tree_bytes(tree) -> int:
                import jax

                return int(sum(
                    np.asarray(leaf).nbytes for leaf in jax.tree.leaves(tree)
                ))

            page_bytes = _tree_bytes(
                plane_model.init_state(plane_model.PlaneDims(1, TP, args.pkts, SP))
            )
            dense_room_bytes = _tree_bytes(
                plane_model.init_state(
                    plane_model.PlaneDims(1, T_MAX, args.pkts, S_MAX)
                )
            )

            rng = np.random.default_rng(9)

            def _sample_room() -> int:
                u = rng.random()
                if u < 0.80:
                    return int(rng.integers(2, 5))
                if u < 0.95:
                    return int(rng.integers(5, 11))
                return 50

            pager = RoomPager(rooms=POOL, tracks=T_MAX, subs=S_MAX,
                              tpage=TP, spage=SP, pool_pages=POOL)
            admitted = 0
            hist = {"le4": 0, "le10": 0, "p50": 0}
            while True:
                p = _sample_room()
                try:
                    pager.alloc_room(admitted, tracks=p, subs=p)
                except CapacityError:
                    break
                admitted += 1
                hist["le4" if p <= 4 else "le10" if p <= 10 else "p50"] += 1
            st = pager.stats()
            pool_bytes = POOL * page_bytes
            dense_rooms = pool_bytes // dense_room_bytes
            ratio = round(admitted / max(dense_rooms, 1), 1)
            hbm_bytes = int(16e9 * 0.9)  # v5e chip, 90% usable for state
            RESULT["paged_capacity"] = {
                "distribution": "80% 2-4p / 15% 5-10p / 5% 50p (seed 9)",
                "pool_pages": POOL,
                "page_bytes": page_bytes,
                "dense_room_bytes": dense_room_bytes,
                "rooms_admitted_paged": admitted,
                "rooms_equal_hbm_dense": int(dense_rooms),
                "room_mix": hist,
                "pages_mapped": st["pages_mapped"],
                "internal_slack_pages": st["internal_slack"],
                "fragmentation_ratio": st["fragmentation_ratio"],
            }
            RESULT["paged_vs_dense_rooms_ratio"] = ratio
            RESULT["rooms_per_chip_realistic"] = int(
                hbm_bytes / pool_bytes * admitted
            )
        except Exception as e:  # noqa: BLE001
            RESULT["paged_capacity_error"] = f"{type(e).__name__}: {e}"
        section_done("paged_capacity", t_sec)

    # -- ragged pooled tick: pay compute only for live pages --------------
    # The fused live-extent tick (ops/paged_kernel behind models/paged
    # paged_plane_tick_fused) schedules one grid step per LIVE page; the
    # stock pooled tick charges the full pool every tick. Fill a pool at
    # the same 80/15/5 distribution, time the fused tick at full
    # occupancy, release half the rooms, time again: work should track
    # live pages, not pool size. On this CPU rig the gathered fallback
    # stands in for the Pallas kernel (same live-extent schedule; the
    # TPU path swaps in via use_pallas).
    if section_ok("paged_kernel", 120):
        t_sec = time.perf_counter()
        try:
            import jax
            import jax.numpy as jnp

            from livekit_server_tpu.models import paged
            from livekit_server_tpu.models import plane as plane_model
            from livekit_server_tpu.runtime.pager import RoomPager
            from livekit_server_tpu.runtime.slots import CapacityError

            T_MAX, S_MAX, TP, SP, K = 64, 64, 4, 8, 8
            POOL = 512
            dims = paged.PagedDims(rooms=POOL, tracks=T_MAX, pkts=K,
                                   subs=S_MAX, tpage=TP, spage=SP,
                                   pool_pages=POOL)
            rng = np.random.default_rng(9)

            def _sample_room() -> int:
                u = rng.random()
                if u < 0.80:
                    return int(rng.integers(2, 5))
                if u < 0.95:
                    return int(rng.integers(5, 11))
                return 50

            pager = RoomPager(rooms=POOL, tracks=T_MAX, subs=S_MAX,
                              tpage=TP, spage=SP, pool_pages=POOL)
            admitted: list[int] = []
            misses = 0
            while misses < 5:
                p = _sample_room()
                try:
                    pager.alloc_room(len(admitted), tracks=p, subs=p)
                except CapacityError:
                    misses += 1
                    continue
                admitted.append(len(admitted))

            def _snap():
                table = paged.PageTable(
                    rooms_pages=jnp.asarray(pager.rooms_pages),
                    tmembers=jnp.asarray(pager.tmembers),
                    pg_room=jnp.asarray(pager.pg_room),
                    pg_tp=jnp.asarray(pager.pg_tp),
                    pg_sp=jnp.asarray(pager.pg_sp),
                )
                live = np.nonzero(pager.pg_room >= 0)[0].astype(np.int32)
                nl = 1 << max(len(live) - 1, 1).bit_length()
                rows = np.concatenate(
                    [live, np.repeat(live[:1], nl - len(live))]
                ).astype(np.int32)
                inv = np.zeros(POOL, np.int32)
                inv[live] = np.arange(len(live), dtype=np.int32)
                return table, live, rows, inv

            def _inputs(salt: int):
                r = np.random.default_rng(100 + salt)
                P = POOL
                pk = (P, TP, K)
                ii = lambda lo, hi, sh: jnp.asarray(  # noqa: E731
                    r.integers(lo, hi, sh), jnp.int32)
                bb = lambda pr, sh: jnp.asarray(r.random(sh) < pr)  # noqa: E731
                ff = lambda lo, hi, sh: jnp.asarray(  # noqa: E731
                    r.uniform(lo, hi, sh), jnp.float32)
                return plane_model.TickInputs(
                    sn=ii(0, 65536, pk), ts=ii(0, 1 << 30, pk),
                    layer=ii(0, 3, pk), temporal=ii(0, 4, pk),
                    keyframe=bb(0.2, pk), layer_sync=bb(0.3, pk),
                    begin_pic=bb(0.4, pk), end_frame=bb(0.4, pk),
                    pid=ii(0, 100, pk), tl0=ii(0, 100, pk),
                    keyidx=ii(0, 30, pk), size=ii(40, 1200, pk),
                    frame_ms=ii(0, 20, pk), audio_level=ii(0, 127, pk),
                    arrival_rtp=ii(0, 1 << 28, pk),
                    ts_jump=jnp.zeros(pk, jnp.int32), valid=bb(0.8, pk),
                    estimate=ff(1e5, 5e6, (P, SP)),
                    estimate_valid=bb(0.5, (P, SP)),
                    nacks=ff(0, 3, (P, SP)), pub_rtt_ms=ff(0, 80, (P, TP)),
                    fb_delay_ms=ff(0, 30, (P, SP)),
                    fb_recv_bps=ff(1e5, 4e6, (P, SP)),
                    fb_valid=bb(0.6, (P, SP)), fb_enabled=bb(0.8, (P, SP)),
                    sub_reset=jnp.zeros((P, SP), bool),
                    pad_num=jnp.zeros((P, SP), jnp.int32),
                    pad_track=jnp.full((P, SP), -1, jnp.int32),
                    tick_ms=jnp.asarray(10, jnp.int32),
                    roll_quality=jnp.asarray(0, jnp.int32),
                )

            inputs = [_inputs(s) for s in range(6)]

            def _time_fused(table, rows, inv):
                tick = jax.jit(lambda s, i: paged.paged_plane_tick_fused(
                    s, i, table, rows, inv, use_pallas=False))
                st = plane_model.init_state(dims.pooled())
                st, out = tick(st, inputs[0])
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                for inp in inputs[1:]:
                    st, out = tick(st, inp)
                jax.block_until_ready(out)
                return round(
                    (time.perf_counter() - t0) / (len(inputs) - 1) * 1e3, 3)

            table_f, live_f, rows_f, inv_f = _snap()
            ms_full = _time_fused(table_f, rows_f, inv_f)

            for r in admitted[::2]:
                pager.release_room(r)
            table_h, live_h, rows_h, inv_h = _snap()
            ms_half = _time_fused(table_h, rows_h, inv_h)

            # Flat-cost reference: the stock pooled tick at the same pool.
            stock = jax.jit(lambda s, i: paged.paged_plane_tick(
                s, i, table_f))
            st = plane_model.init_state(dims.pooled())
            st, out = stock(st, inputs[0])
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for inp in inputs[1:]:
                st, out = stock(st, inp)
            jax.block_until_ready(out)
            ms_stock = round(
                (time.perf_counter() - t0) / (len(inputs) - 1) * 1e3, 3)

            RESULT["paged_kernel"] = {
                "distribution": "80% 2-4p / 15% 5-10p / 5% 50p (seed 9)",
                "mode": "cpu_fallback",
                "pool_pages": POOL,
                "live_pages_full": int(len(live_f)),
                "grid_steps_full": int(len(rows_f)),
                "tick_ms_full": ms_full,
                "live_pages_half": int(len(live_h)),
                "grid_steps_half": int(len(rows_h)),
                "tick_ms_half": ms_half,
                "stock_tick_ms": ms_stock,
                "half_over_full_work_ratio": round(
                    ms_half / max(ms_full, 1e-9), 3),
            }
            RESULT["paged_kernel_tick_ms"] = ms_full
        except Exception as e:  # noqa: BLE001
            RESULT["paged_kernel_error"] = f"{type(e).__name__}: {e}"
        section_done("paged_kernel", t_sec)

    # -- batched audio mix (ops/mix — BASELINE config 2's MCU seat) -------
    # G.711 decode + active-speaker einsum mix + µ-law re-encode at the
    # 1-room × 50-participant shape, all 50 subscribers mixed.
    if section_ok("audio_mix", 25):
        t_sec = time.perf_counter()
        try:
            import jax.numpy as jnp

            from livekit_server_tpu.ops import mix as mix_ops

            Rm, Tm, Sm, Nm = 1, 50, 50, 960  # 20 ms @ 48 kHz
            rngm = np.random.default_rng(2)

            @jax.jit
            def mix_step(payload, codec, level, active, sub_track, gain):
                pcm = mix_ops.decode_tick(payload, codec)
                out = mix_ops.mix_tick(pcm, level, active, sub_track, gain)
                return mix_ops.encode_ulaw(out)

            # Salted per-call payloads: the axon terminal caches identical
            # executions, so repeated args would time a no-op.
            margs = [
                (
                    jnp.asarray(rngm.integers(0, 256, (Rm, Tm, Nm)), jnp.uint8),
                    jnp.zeros((Rm, Tm), jnp.int32),
                    jnp.asarray(rngm.random((Rm, Tm)), jnp.float32),
                    jnp.asarray(rngm.random((Rm, Tm)) < 0.5),
                    jnp.asarray(np.arange(Sm)[None, :] % Tm, jnp.int32),
                    jnp.ones((Rm, Tm), jnp.float32),
                )
                for _ in range(17)
            ]
            out = mix_step(*margs[0])
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            trials = 16
            for i in range(trials):
                out = mix_step(*margs[1 + i])
            int(np.asarray(out)[0, 0, 0])
            RESULT["audio_mix_50p_tick_ms"] = round(
                (time.perf_counter() - t0) / trials * 1000.0, 3
            )
        except Exception as e:  # noqa: BLE001
            RESULT["audio_mix_error"] = f"{type(e).__name__}"
        section_done("audio_mix", t_sec)

    # -- batched audio mix at the 1000-room MCU shape ---------------------
    # runtime/mixer.py's device path (_device_mix) batches every enabled
    # room into one presence/self-exclusion einsum once the per-frame
    # room count crosses DEVICE_MIX_MIN_ROOMS. Time that exact
    # contraction at 1000 rooms × 4 tracks × 4 subscribers × 20 ms
    # (the small-room population where a per-room host loop stops
    # holding the frame deadline).
    if section_ok("audio_mix_1kroom", 30):
        t_sec = time.perf_counter()
        try:
            import jax.numpy as jnp

            from livekit_server_tpu.runtime.mixer import _device_mix

            Rk, Tk, Sk, Nk = 1000, 4, 4, 960  # 20 ms @ 48 kHz
            rngk = np.random.default_rng(3)
            mixk = _device_mix(Tk, Sk, Nk)
            # Salted per-call args (identical executions can be cached).
            kargs = [
                (
                    jnp.asarray(rngk.integers(
                        -32768, 32768, (Rk, Tk, Nk)), jnp.float32),
                    jnp.asarray(rngk.random((Rk, Tk)) < 0.8),
                    jnp.asarray(rngk.integers(
                        0, Tk + 1, (Rk, Sk)), jnp.int32),
                )
                for _ in range(9)
            ]
            out = mixk(*kargs[0])
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            trials = 8
            for i in range(trials):
                out = mixk(*kargs[1 + i])
            float(np.asarray(out)[0, 0, 0])
            RESULT["audio_mix_1kroom_tick_ms"] = round(
                (time.perf_counter() - t0) / trials * 1000.0, 3
            )
        except Exception as e:  # noqa: BLE001
            RESULT["audio_mix_1kroom_error"] = f"{type(e).__name__}"
        section_done("audio_mix_1kroom", t_sec)

    RESULT["bench_total_s"] = round(time.perf_counter() - _T0, 1)
    emit()
    # Compact scoreboard summary, printed LAST: the driver keeps the final
    # complete JSON line of stdout, and the full RESULT record grew past
    # the point where truncation mid-line was a real failure mode (rounds
    # 4-5 survived only as clipped text). Headline scalars only — the full
    # record is the emit() line right above this one.
    summary = {"summary": True}
    for key in ("metric", "value", "unit", "vs_baseline", "device_tick_ms",
                "host_egress_pps", "wire_host_egress_pps", "p50_wire_ms",
                "p99_wire_ms", "p99_wire_local_ms",
                "northstar_10240rooms_50subs_tick_ms",
                "wire_shape_device_tick_ms", "audio_mix_50p_tick_ms",
                "audio_mix_1kroom_tick_ms", "paged_kernel_tick_ms",
                "rooms_per_chip_realistic", "paged_vs_dense_rooms_ratio",
                "bench_total_s"):
        if key in RESULT:
            summary[key] = RESULT[key]
    if "egress_plane" in RESULT:
        summary["egress_plane"] = RESULT["egress_plane"]
    if "wire_ramp" in RESULT:
        summary["wire_ramp_max_rooms_ok"] = RESULT["wire_ramp"].get(
            "max_rooms_ok", 0
        )
    # Capacity/SLO curve from the fleet traffic twin: one row per
    # offered-load step with the headline robustness SLOs, plus the knee
    # (first load where admission dips below ~100%).
    if "fleet_twin" in RESULT:
        ft = RESULT["fleet_twin"]
        summary["fleet_twin"] = {
            "capacity_knee_load": ft.get("capacity_knee_load"),
            "steps": [
                {
                    "load": s.get("offered_load"),
                    "admission_rate": s.get("admission_rate"),
                    "audio_continuity": s.get("audio_continuity"),
                    "dup_wire_packets": s.get("dup_wire_packets"),
                    "wire_p99_ms": s.get("wire_p99_ms"),
                    "rung_residency": s.get("rung_residency"),
                    "recovery_ticks": s.get("recovery_ticks"),
                }
                for s in ft.get("steps", [])
            ],
        }
    # Sampled wire-latency stage decomposition (flight-recorder plane):
    # p50/p99 per stage from the preferred wire section that ran.
    for wk in ("wire_local", "wire"):
        st = (RESULT.get(wk) or {}).get("stages")
        if st:
            summary["wire_stages"] = {
                s: {"p50_ms": v.get("p50_ms"), "p99_ms": v.get("p99_ms")}
                for s, v in st.items()
            }
            break
    # Recompile watchdog from the preferred wire run: post-warmup XLA
    # compiles during the measurement window (0 = the steady-state tick
    # path never retraced) and the warmup window's total compile time.
    for wk in ("wire_local", "wire"):
        w = RESULT.get(wk) or {}
        if "xla_compiles_post_warmup" in w:
            summary["xla_compiles_post_warmup"] = w["xla_compiles_post_warmup"]
            summary["xla_warmup_compile_ms"] = w.get("xla_warmup_compile_ms")
            break
    if "skipped" in RESULT:
        summary["skipped"] = sorted(RESULT["skipped"])
    sys.stdout.write(json.dumps(summary) + "\n")
    sys.stdout.flush()


if __name__ == "__main__":
    main()
