"""Per-block attribution of the media-plane tick at a given shape.

Times each sub-block of `_room_tick` standalone (vmapped over rooms, jitted,
donated where possible) with the same two-window slope method bench.py uses,
so per-dispatch tunnel cost cancels. Run:

    python tools/profile_tick.py --shape cfg4
    python tools/profile_tick.py --shape northstar
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.models import plane, synth
from livekit_server_tpu.ops import (
    allocation,
    audio,
    bwe,
    pacer,
    red,
    rtpmunger,
    rtpstats,
    selector,
    streamtracker,
    vp8,
)

SHAPES = {
    "cfg4": (
        plane.PlaneDims(1024, 10, 8, 10),
        synth.TrafficSpec(video_tracks=2, audio_tracks=8, tick_ms=20,
                          video_kbps=1500, svc=True),
    ),
    "northstar": (
        plane.PlaneDims(10240, 8, 16, 50),
        synth.TrafficSpec(video_tracks=2, audio_tracks=6, tick_ms=20,
                          video_kbps=1500, svc=True),
    ),
    "default": (
        plane.PlaneDims(128, 8, 16, 16),
        synth.TrafficSpec(video_tracks=4, audio_tracks=4, tick_ms=20,
                          video_kbps=3000),
    ),
}


def timeit(fn, args, n=8, label=""):
    """Two-window slope: run n and 3n chained calls, report (t3 - t1)/(2n)."""
    out = fn(*args)
    jax.block_until_ready(out)

    def run(k):
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = fn(*args)
        jax.block_until_ready(o)
        return time.perf_counter() - t0

    t_a = run(n)
    t_b = run(3 * n)
    ms = (t_b - t_a) / (2 * n) * 1000.0
    print(f"{label:42s} {ms:9.3f} ms")
    return ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", default="cfg4", choices=list(SHAPES))
    ap.add_argument("--n", type=int, default=8)
    args = ap.parse_args()

    import bench
    bench._setup_compile_cache()

    dims, spec = SHAPES[args.shape]
    R, T, K, S = dims
    L = plane.MAX_LAYERS
    n = args.n

    state = synth.make_state(dims, spec)
    traffic = synth.init_traffic(dims, spec)
    traffic, inp = synth.next_tick(traffic, dims, spec, tick_index=7)
    inp = jax.tree.map(jnp.asarray, inp)
    print(f"shape={args.shape} dims={dims}")

    # ---- full tick (the reference number) --------------------------------
    pkt, fb, tf, tick_ms, roll = plane.pack_tick_inputs(inp)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def full(state, pkt, fb, tf, tick_ms, roll):
        i = plane.unpack_tick_inputs(pkt, fb, tf, tick_ms, roll)
        state, out = plane.media_plane_tick(state, i)
        return state, plane.pack_tick_outputs(out).astype(jnp.int64).sum()

    st = state
    def full_call(pkt, fb, tf):
        nonlocal st
        st, chk = full(st, pkt, fb, tf, tick_ms, roll)
        return chk
    timeit(full_call, (pkt, fb, tf), n, "FULL tick (packed, donated)")

    state = synth.make_state(dims, spec)

    # ---- 1. rtpstats -----------------------------------------------------
    eff_layer = jnp.where(state.meta.is_svc[..., None],
                          0, jnp.clip(inp.layer, 0, L - 1))

    @jax.jit
    def stats_block(stats, sn, ts, size, arr, valid, eff_layer):
        lanes = jnp.arange(L, dtype=jnp.int32)[None, None, None, :]
        def to_streams(x, fill):
            routed = jnp.where(eff_layer[..., None] == lanes, x[..., None],
                               jnp.asarray(fill, x.dtype))
            return routed.transpose(0, 1, 3, 2).reshape(R, T * L, K)
        out = jax.vmap(rtpstats.update_tick)(
            stats, to_streams(sn, 0), to_streams(ts, 0),
            to_streams(size, 0), to_streams(arr, 0),
            to_streams(valid, False))
        return out
    timeit(lambda *a: stats_block(*a),
           (state.stats, inp.sn, inp.ts, inp.size, inp.arrival_rtp,
            inp.valid, eff_layer), n, "1. rtpstats.update_tick (+routing)")

    # ---- 2. streamtracker ------------------------------------------------
    @jax.jit
    def tracker_block(tracker, layer, valid, size, begin_pic, tick_ms):
        true_layer = jnp.clip(layer, 0, L - 1)
        lanes = jnp.arange(L, dtype=jnp.int32)[None, None, None, :]
        t_lane = true_layer[..., None] == lanes
        def to_tracker(x, pred):
            routed = jnp.where(t_lane & pred[..., None], x[..., None], 0)
            return jnp.sum(routed, axis=2).reshape(R, T * L)
        ones_k = jnp.ones((R, T, K), jnp.int32)
        st_pkts = to_tracker(ones_k, valid)
        st_bytes = to_tracker(size, valid)
        st_frames = to_tracker(ones_k, valid & begin_pic)
        return jax.vmap(
            lambda tr, p, b, f: streamtracker.update_tick(
                tr, streamtracker.TrackerParams(), p, b, tick_ms, frames=f)
        )(tracker, st_pkts, st_bytes, st_frames)
    timeit(lambda *a: tracker_block(*a),
           (state.tracker, inp.layer, inp.valid, inp.size, inp.begin_pic,
            inp.tick_ms), n, "2. streamtracker (+routing)")

    # ---- 3. fused forward-decision kernel (production phase 0) -----------
    base_m = (np.asarray(state.ctrl.subscribed)
              & ~np.asarray(state.ctrl.sub_muted)
              & (np.asarray(state.meta.published)
                 & ~np.asarray(state.meta.pub_muted))[:, :, None])

    @jax.jit
    def sel_block(sel, is_svc, is_video, base, layer, temporal, kf, sync, eof,
                  valid, size):
        return selector.decide_rooms(
            sel, is_svc, is_video, base, layer, temporal, kf, sync, eof,
            valid, size, wire_overhead=pacer.WIRE_OVERHEAD_BYTES)
    timeit(lambda *a: sel_block(*a),
           (state.sel, state.meta.is_svc, state.meta.is_video,
            jnp.asarray(base_m), inp.layer, inp.temporal,
            inp.keyframe, inp.layer_sync, inp.end_frame, inp.valid, inp.size),
           n, "3. selector.decide_rooms (fused kernel)")

    # ---- 4. munger + vp8 -------------------------------------------------
    fwd = jnp.ones((R, T, K, S), bool)
    drop = jnp.zeros((R, T, K, S), bool)
    switch = jnp.zeros((R, T, K, S), bool)

    tile_ts = lambda tree: jax.tree.map(  # noqa: E731
        lambda x: jnp.broadcast_to(x, (R, T) + x.shape).copy(), tree)
    munger_st = tile_ts(rtpmunger.init_state(S))
    vp8_st = tile_ts(vp8.init_state(S))

    @jax.jit
    def munger_block(munger, sn, ts, valid, fwd, drop, switch, ts_jump):
        return jax.vmap(jax.vmap(rtpmunger.munge_tick))(
            munger, sn, ts, valid, fwd, drop, switch, ts_jump)
    timeit(lambda *a: munger_block(*a),
           (munger_st, inp.sn, inp.ts, inp.valid, fwd, drop, switch,
            inp.ts_jump), n, "4. rtpmunger.munge_tick (retired from tick)")

    @jax.jit
    def vp8_block(vst, pid, tl0, keyidx, begin, valid, fwd, drop, switch):
        return jax.vmap(jax.vmap(vp8.munge_tick))(
            vst, pid, tl0, keyidx, begin, valid, fwd, drop, switch)
    timeit(lambda *a: vp8_block(*a),
           (vp8_st, inp.pid, inp.tl0, inp.keyidx, inp.begin_pic,
            inp.valid, fwd, drop, switch), n, "5. vp8.munge_tick (retired from tick)")

    # ---- 6. allocation (pallas, vmapped) ---------------------------------
    bitrates = jnp.ones((R, T, 4, 4), jnp.float32) * 1e5
    budget = jnp.ones((R, S), jnp.float32) * 5e6

    @jax.jit
    def alloc_block(bitrates, ms, mt, muted, budget):
        return allocation.allocate_budget_rooms(bitrates, ms, mt, muted, budget)
    timeit(lambda *a: alloc_block(*a),
           (bitrates, state.ctrl.max_spatial.transpose(0, 2, 1),
            state.ctrl.max_temporal.transpose(0, 2, 1),
            jnp.zeros((R, S, T), bool), budget),
           n, "6. allocation.allocate_budget_batch")

    # ---- 7. bwe + pacer --------------------------------------------------
    @jax.jit
    def bwe_block(bst, dst, pst, est, estv, nacks, fbd, fbr, fbv, fbe, tick_ms):
        pkts = jnp.ones((R, S), jnp.float32)
        b2, cong, trend, budget = jax.vmap(
            lambda a, b, c, d, e: bwe.update_tick(
                a, bwe.BWEParams(), b, c, d, e)
        )(bst, est, estv, pkts, nacks)
        d2, rate, over, act = jax.vmap(
            lambda a, b, c, d, e, f: bwe.delay_update_tick(
                a, bwe.DelayBWEParams(), b, c, d, e, f, tick_ms)
        )(dst, fbd, fbr, fbv, fbe, pkts)
        p2, allowed, backlog = jax.vmap(
            lambda a, b, c: pacer.update_tick(
                a, pacer.PacerParams(), b, c, tick_ms)
        )(pst, pkts * 100, budget)
        return b2, d2, p2, cong, budget, allowed
    timeit(lambda *a: bwe_block(*a),
           (state.bwe_state, state.delay_bwe, state.pacer_state,
            inp.estimate, inp.estimate_valid, inp.nacks, inp.fb_delay_ms,
            inp.fb_recv_bps, inp.fb_valid, inp.fb_enabled, inp.tick_ms),
           n, "7. bwe+delay+pacer")

    # ---- 8. RED plan -----------------------------------------------------
    @jax.jit
    def red_block(rst, sn, ts, size, audio_valid):
        return jax.vmap(red.encode_plan_tick)(rst, sn, ts, size, audio_valid)
    timeit(lambda *a: red_block(*a),
           (state.red_state, inp.sn, inp.ts, inp.size,
            inp.valid & ~state.meta.is_video[..., None]),
           n, "8. red.encode_plan_tick")

    # ---- 9. audio --------------------------------------------------------
    @jax.jit
    def audio_block(ast, level, frame_ms, valid, tick_ms):
        a2, linear, act = jax.vmap(
            lambda a, b, c, d: audio.observe_tick(
                a, audio.AudioLevelParams(), b, c, d, tick_ms)
        )(ast, level, frame_ms, valid)
        lv, tr = jax.vmap(lambda lin, a: audio.top_speakers(
            jnp.where(a, lin, 0.0), min(plane.SPEAKER_TOP_K, T)))(linear, act)
        return a2, lv, tr
    timeit(lambda *a: audio_block(*a),
           (state.audio_state, inp.audio_level, inp.frame_ms,
            inp.valid & ~state.meta.is_video[..., None], inp.tick_ms),
           n, "9. audio levels + top-k")

    # ---- 10. egress compaction (RETIRED from the tick: these two blocks
    # measure the r1-r4 device-side compaction designs for the record) ----
    send = fwd & (jnp.arange(S)[None, None, None, :] < 4)
    cap = min(T * K * S, max(128, T * K * 4))

    @jax.jit
    def compact_block(send, sn, ts):
        flat = send.reshape(R, -1)
        def one(fs, osn, ots):
            (idx,) = jnp.nonzero(fs, size=cap, fill_value=-1)
            safe = jnp.maximum(idx, 0)
            hit = idx >= 0
            return (idx.astype(jnp.int32),
                    jnp.where(hit, osn.reshape(-1)[safe], 0),
                    jnp.where(hit, ots.reshape(-1)[safe], 0))
        osn = jnp.broadcast_to(sn[..., None], (R, T, K, S))
        return jax.vmap(one)(flat, osn, jnp.broadcast_to(ts[..., None], (R, T, K, S)))
    timeit(lambda *a: compact_block(*a), (send, inp.sn, inp.ts),
           n, "10. egress compaction (nonzero+gather)")

    # ---- 11. compaction via cumsum+searchsorted (candidate) --------------
    @jax.jit
    def compact2_block(send, sn, ts):
        flat = send.reshape(R, -1).astype(jnp.int32)
        csum = jnp.cumsum(flat, axis=1)                      # [R, N]
        want = jnp.arange(1, cap + 1, dtype=jnp.int32)[None, :]
        idx = jax.vmap(lambda c, w: jnp.searchsorted(c, w, side="left"))(
            csum, jnp.broadcast_to(want, (R, cap)))
        total = csum[:, -1]
        hit = want[0][None, :] <= total[:, None]
        idx = jnp.where(hit, idx, -1).astype(jnp.int32)
        safe = jnp.maximum(idx, 0)
        osn = jnp.broadcast_to(sn[..., None], (R, T, K, S)).reshape(R, -1)
        ots = jnp.broadcast_to(ts[..., None], (R, T, K, S)).reshape(R, -1)
        g = lambda x: jnp.where(hit, jnp.take_along_axis(x, safe, axis=1), 0)
        return idx, g(osn), g(ots)
    timeit(lambda *a: compact2_block(*a), (send, inp.sn, inp.ts),
           n, "11. compaction (cumsum+searchsorted)")

    # ---- 12. output packing (concatenate) --------------------------------
    state2 = synth.make_state(dims, spec)
    pkt2, fb2, tf2, _, _ = plane.pack_tick_inputs(inp)

    @jax.jit
    def outputs_only(state, pkt, fb, tf):
        i = plane.unpack_tick_inputs(pkt, fb, tf, tick_ms, roll)
        _, out = plane.media_plane_tick(state, i)
        return out

    @jax.jit
    def outputs_packed(state, pkt, fb, tf):
        i = plane.unpack_tick_inputs(pkt, fb, tf, tick_ms, roll)
        _, out = plane.media_plane_tick(state, i)
        return plane.pack_tick_outputs(out)

    timeit(lambda *a: outputs_only(*a), (state2, pkt2, fb2, tf2),
           n, "12a. tick, outputs UNPACKED (no donate)")
    timeit(lambda *a: outputs_packed(*a), (state2, pkt2, fb2, tf2),
           n, "12b. tick, outputs packed (no donate)")

    # ---- 13. mask merges + padding + quality (leftover algebra) ----------
    @jax.jit
    def merge_block(is_video, valid, base, v_fwd, v_drop):
        a_fwd = valid[..., None] & base[:, :, None, :]
        fwd = jnp.where(is_video[..., None, None], v_fwd & base[:, :, None, :], a_fwd)
        drop = jnp.where(is_video[..., None, None], v_drop & base[:, :, None, :], False)
        ev = jnp.sum(fwd, dtype=jnp.int32)
        return fwd, drop, ev
    base = jnp.ones((R, T, S), bool)
    timeit(lambda *a: merge_block(*a),
           (state.meta.is_video, inp.valid, base, fwd, drop),
           n, "13. mask merges")
    print("done")


if __name__ == "__main__":
    main()
