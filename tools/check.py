"""graftcheck runner — the repo's pre-commit / tier-1 static gate.

    python -m tools.check              # lint + devicecheck + compileall
    python -m tools.check --json       # findings as JSON on stdout
    python -m tools.check --baseline   # (re)write the committed baseline
    python -m tools.check --resnapshot # rewrite the devicecheck contracts

Exit codes: 0 clean, 1 findings (or compile errors), 2 stale baseline /
config problems. The baseline may only shrink: a baselined finding that
no longer reproduces must be removed from the baseline file, otherwise
the run fails with the stale entries listed. The same shrink-only
contract covers inline suppressions (a `# graftcheck: disable=` that no
longer suppresses anything is itself a finding) and the devicecheck
contract baseline (a registered entry that disappears, or a committed
contract the live tree no longer matches, fails the run).
"""

from __future__ import annotations

import argparse
import compileall
import json
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tools.check", description=__doc__)
    ap.add_argument("--baseline", action="store_true",
                    help="rewrite the baseline file from current findings")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip the compileall pass (pure lint)")
    ap.add_argument("--no-native", action="store_true",
                    help="skip the native toolchain smoke (build + ABI)")
    ap.add_argument("--latency", action="store_true",
                    help="also run the slow express-lane latency smoke "
                         "(tests/test_latency_smoke.py; real sockets, ~30s)")
    ap.add_argument("--twin-smoke", action="store_true",
                    help="also run the ~2s traffic-twin micro-scenario "
                         "end-to-end (runtime/traffic_twin.py --smoke)")
    ap.add_argument("--trace-schema", action="store_true",
                    help="also validate the trace-export schema on a tiny "
                         "traced run (telemetry/trace_export --selftest)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset, e.g. GC01,GC04")
    ap.add_argument("--no-devicecheck", action="store_true",
                    help="skip the abstract-eval compile-contract pass "
                         "(eval_shape + jaxpr audit of the @device_entry "
                         "registry; needs jax importable)")
    ap.add_argument("--resnapshot", action="store_true",
                    help="rewrite tools/devicecheck_baseline.json from "
                         "the live tree (the sanctioned way to land an "
                         "intentional contract change)")
    args = ap.parse_args(argv)

    sys.path.insert(0, str(REPO_ROOT))
    from livekit_server_tpu.analysis import (
        core,
        diff_baseline,
        load_baseline,
        load_project,
        run_all,
        write_baseline,
    )

    t0 = time.perf_counter()
    config = core.load_config(REPO_ROOT)
    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        bad = [r for r in rules if r not in core.RULES]
        if bad:
            print(f"unknown rules: {', '.join(bad)}", file=sys.stderr)
            return 2
    project = load_project(REPO_ROOT, config.paths)
    stale_suppressions: list[core.Finding] = []
    findings = run_all(project, config, rules,
                       stale_suppressions=stale_suppressions)

    baseline_path = REPO_ROOT / config.baseline
    if args.baseline:
        write_baseline(baseline_path, findings, project)
        print(f"baseline written: {len(findings)} finding(s) -> "
              f"{config.baseline}")
        return 0

    new, stale = diff_baseline(findings, load_baseline(baseline_path), project)
    # Stale inline suppressions ride the same shrink-only contract as
    # the baseline: a disable= that suppresses nothing must go.
    new = list(new) + stale_suppressions

    # Abstract-eval compile contracts over the @device_entry registry
    # (eval_shape + jaxpr cost + donation audit at canonical dims).
    device_findings: list[core.Finding] = []
    device_stale: list[str] = []
    device_s = 0.0
    if not args.no_devicecheck:
        try:
            import jax  # noqa: F401  (pay the import before the timer)

            from livekit_server_tpu.analysis import devicecheck
        except ImportError as exc:   # jax absent: the AST gates still ran
            print(f"devicecheck: skipped (jax unavailable: {exc})",
                  file=sys.stderr)
            devicecheck = None
        if devicecheck is not None:
            d0 = time.perf_counter()
            device_findings, device_stale = devicecheck.run_check(
                REPO_ROOT, resnapshot=args.resnapshot
            )
            device_s = time.perf_counter() - d0
        if args.resnapshot:
            print(f"devicecheck baseline rewritten "
                  f"({device_s:.2f}s) -> tools/devicecheck_baseline.json")
        new.extend(device_findings)

    # Bytecode-compile the tree: catches syntax errors in files the
    # analyzers never import (plugins, dead branches) — cheap and total.
    compiled_ok = True
    if not args.no_compile:
        compiled_ok = compileall.compile_dir(
            str(REPO_ROOT / "livekit_server_tpu"), quiet=2, force=False
        )

    # Native toolchain smoke: compile every native/*.cpp, load the .so's,
    # cross-check the baked ABI version symbols against the ctypes layer,
    # and run one tiny build/walk through each library. Catches a broken
    # compiler, a stale .so after an ABI bump, and signature drift —
    # failures the pure-Python gates above can't see.
    native_failures: list[str] = []
    if not args.no_native:
        try:
            from livekit_server_tpu import native as native_mod

            native_failures = native_mod.native_smoke()
        except Exception as exc:  # toolchain totally absent ⇒ report, fail
            native_failures = [f"native smoke crashed: {exc!r}"]
        # Egress shard planner × paged extents: the munge/seal walk cuts
        # on room boundaries; with the paged plane, a room's entry count
        # tracks its RAGGED page extent, not the dense axis. Verify the
        # planner still tiles exactly and never splits a room when fed an
        # extent-skewed entry distribution from a real pager.
        native_failures.extend(_pager_shard_smoke())
        # Ragged paged-tick kernel: interpret-mode compile + run on a
        # tiny page table, decide bits cross-checked vs the fallback.
        native_failures.extend(_paged_kernel_smoke())

    # Opt-in latency smoke: the slow-marked express-lane wire-p99 test
    # (excluded from tier-1 by the `slow` marker). Runs in a subprocess
    # so a hung serving loop can't wedge the gate.
    latency_failures: list[str] = []
    if args.latency:
        import os
        import subprocess

        proc = subprocess.run(
            [sys.executable, "-m", "pytest",
             "tests/test_latency_smoke.py", "-q", "-m", "slow",
             "-p", "no:cacheprovider"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": os.environ.get(
                "JAX_PLATFORMS", "cpu")},
        )
        if proc.returncode != 0:
            tail = "\n".join((proc.stdout or "").splitlines()[-15:])
            latency_failures = [f"latency smoke failed "
                                f"(exit {proc.returncode}):\n{tail}"]
    native_failures.extend(latency_failures)

    # Opt-in trace-schema gate: run a tiny CPU plane with tracing on,
    # export the span ring as Chrome trace JSON, and validate required
    # fields + strict span nesting. Subprocess for the same hang-proofing
    # as the latency smoke.
    if args.trace_schema:
        import os
        import subprocess

        proc = subprocess.run(
            [sys.executable, "-m",
             "livekit_server_tpu.telemetry.trace_export", "--selftest"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": os.environ.get(
                "JAX_PLATFORMS", "cpu")},
        )
        if proc.returncode != 0:
            tail = "\n".join((proc.stdout or "").splitlines()[-15:])
            native_failures.append(
                f"trace schema selftest failed "
                f"(exit {proc.returncode}):\n{tail}"
            )

    # Opt-in traffic-twin smoke: the micro-scenario (one churn segment,
    # one flash crowd) replayed end-to-end through a real single-node
    # server in virtual time. Exit 0 requires zero audio gaps, zero
    # duplicate wire packets, and at least one admitted join. Subprocess
    # for the same hang-proofing as the latency smoke.
    if args.twin_smoke:
        import os
        import subprocess

        proc = subprocess.run(
            [sys.executable, "-m",
             "livekit_server_tpu.runtime.traffic_twin", "--smoke"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": os.environ.get(
                "JAX_PLATFORMS", "cpu")},
        )
        if proc.returncode != 0:
            tail = "\n".join((proc.stdout or "").splitlines()[-15:])
            native_failures.append(
                f"twin smoke failed (exit {proc.returncode}):\n{tail}"
            )

    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in new],
            "stale_baseline": stale,
            "stale_device_contracts": device_stale,
            "compile_ok": bool(compiled_ok),
            "native_failures": native_failures,
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"STALE baseline entry (fixed? remove it): "
                  f"{e.get('rule')} {e.get('path')}: {e.get('content')}")
        for name in device_stale:
            print(f"STALE device contract (entry gone? --resnapshot): "
                  f"{name}")
        if not compiled_ok:
            print("compileall: errors (see above)")
        for msg in native_failures:
            print(f"native: {msg}")
        dt = time.perf_counter() - t0
        ok = not (new or stale or device_stale or native_failures) \
            and compiled_ok
        status = "clean" if ok else "FAILED"
        print(f"graftcheck: {len(new)} finding(s), {len(stale)} stale "
              f"baseline entr(ies), {len(device_stale)} stale device "
              f"contract(s), {len(native_failures)} native failure(s), "
              f"{len(project.files)} files in {dt:.2f}s "
              f"(devicecheck {device_s:.2f}s) — {status}")

    if stale or device_stale:
        return 2
    if new or not compiled_ok or native_failures:
        return 1
    return 0


def _pager_shard_smoke() -> list[str]:
    """Cross-check the egress plane's entry planner against paged room
    extents: allocate a mixed-size room population through a RoomPager,
    synthesize a room-ascending egress entry column where each room's
    entry count equals its paged sub extent, and assert for several
    shard widths that the plan (a) tiles [0, n) with no gap or overlap
    and (b) keeps every room on exactly one shard. Pure host math —
    runs even when the C++ toolchain is absent."""
    import numpy as np

    from livekit_server_tpu.runtime.egress_plane import EgressPlane
    from livekit_server_tpu.runtime.pager import RoomPager

    failures: list[str] = []
    pager = RoomPager(rooms=32, tracks=16, subs=32, tpage=4, spage=8,
                      pool_pages=64)
    # 80/15/5-ish population: mostly tiny rooms, a few big ones.
    sizes = [(1, 2)] * 12 + [(2, 10)] * 4 + [(8, 30)] * 2
    for row, (tr, sb) in enumerate(sizes):
        pager.alloc_room(row, tracks=tr, subs=sb)
    rooms_col = np.concatenate([
        np.full(pager.extent(row).subs, row, np.int32)
        for row, _ in enumerate(sizes)
    ])
    for shards in (1, 2, 3, 5, 8):
        plane = EgressPlane(shards=shards, multicast_seal=False)
        lo, hi = plane.entry_plan(rooms_col)
        if lo[0] != 0 or hi[-1] != len(rooms_col) or not (lo[1:] == hi[:-1]).all():
            failures.append(
                f"pager shard smoke: entry_plan({shards}) does not tile "
                f"[0, {len(rooms_col)}): lo={lo.tolist()} hi={hi.tolist()}"
            )
            continue
        for a, b in zip(lo, hi):
            seg = rooms_col[a:b]
            if len(seg) == 0:
                continue
            prev_seg = rooms_col[:a]
            if len(prev_seg) and prev_seg[-1] == seg[0]:
                failures.append(
                    f"pager shard smoke: shards={shards} splits room "
                    f"{int(seg[0])} across a cut at entry {int(a)}"
                )
    return failures


def _paged_kernel_smoke() -> list[str]:
    """Compile-and-run the ragged paged-tick kernel (ops/paged_kernel.py)
    in Pallas interpret mode on a tiny hand-built page table, and check
    the forward decision bits against the gathered CPU fallback. Catches
    a kernel that no longer traces (Mosaic/Pallas API drift) and decide
    algebra divergence, without needing a TPU."""
    import numpy as np

    try:
        import jax.numpy as jnp

        from livekit_server_tpu.models import paged, plane
        from livekit_server_tpu.ops import paged_kernel

        PD = paged.PagedDims(rooms=2, tracks=4, pkts=2, subs=8,
                             tpage=2, spage=4, pool_pages=8)
        P, TP, K, SP = 8, 2, 2, 4
        st = plane.init_state(PD.pooled())
        sub = np.zeros((P, TP, SP), bool)
        sub[[0, 2, 3]] = True
        pub = np.zeros((P, TP), bool)
        pub[[0, 2, 3]] = True
        st = st._replace(
            meta=st.meta._replace(published=jnp.asarray(pub)),
            ctrl=st.ctrl._replace(subscribed=jnp.asarray(sub)),
        )
        rng = np.random.default_rng(11)
        z = lambda sh, dt=np.int32: jnp.zeros(sh, dt)
        inp = plane.TickInputs(
            sn=jnp.asarray(rng.integers(0, 1000, (P, TP, K)), jnp.int32),
            ts=z((P, TP, K)), layer=z((P, TP, K)), temporal=z((P, TP, K)),
            keyframe=z((P, TP, K), bool), layer_sync=z((P, TP, K), bool),
            begin_pic=z((P, TP, K), bool), end_frame=z((P, TP, K), bool),
            pid=z((P, TP, K)), tl0=z((P, TP, K)), keyidx=z((P, TP, K)),
            size=jnp.full((P, TP, K), 100, jnp.int32),
            frame_ms=z((P, TP, K)), audio_level=z((P, TP, K)),
            arrival_rtp=z((P, TP, K)), ts_jump=z((P, TP, K)),
            valid=jnp.ones((P, TP, K), bool),
            estimate=z((P, SP), np.float32),
            estimate_valid=z((P, SP), bool), nacks=z((P, SP), np.float32),
            pub_rtt_ms=z((P, TP), np.float32),
            fb_delay_ms=z((P, SP), np.float32),
            fb_recv_bps=z((P, SP), np.float32), fb_valid=z((P, SP), bool),
            fb_enabled=z((P, SP), bool), sub_reset=z((P, SP), bool),
            pad_num=z((P, SP)), pad_track=z((P, SP)) - 1,
            tick_ms=jnp.asarray(10, jnp.int32),
            roll_quality=jnp.asarray(0, jnp.int32),
        )
        base = st.ctrl.subscribed & ~st.ctrl.sub_muted & (
            st.meta.published & ~st.meta.pub_muted)[:, :, None]
        live = np.array([0, 2, 3, 0], np.int32)  # pow2-padded live rows
        ik = paged_kernel.decide_pages(
            st.sel, st.meta.is_svc, st.meta.is_video, base, inp, live,
            wire_overhead=42, use_pallas=False, interpret=True)
        fb = paged_kernel.decide_pages(
            st.sel, st.meta.is_svc, st.meta.is_video, base, inp, live,
            wire_overhead=42, use_pallas=False)
        for f in ("send_bits", "drop_bits", "need_kf", "pkts_sent"):
            a, b = np.asarray(getattr(ik, f)), np.asarray(getattr(fb, f))
            if not np.array_equal(a, b):
                return [f"paged kernel smoke: interpret vs fallback "
                        f"diverge on {f}"]
    except Exception as exc:
        return [f"paged kernel smoke crashed: {exc!r}"]
    return []


if __name__ == "__main__":
    raise SystemExit(main())
