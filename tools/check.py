"""graftcheck runner — the repo's pre-commit / tier-1 static gate.

    python -m tools.check              # lint + compileall; exit 0 iff clean
    python -m tools.check --json       # findings as JSON on stdout
    python -m tools.check --baseline   # (re)write the committed baseline

Exit codes: 0 clean, 1 findings (or compile errors), 2 stale baseline /
config problems. The baseline may only shrink: a baselined finding that
no longer reproduces must be removed from the baseline file, otherwise
the run fails with the stale entries listed.
"""

from __future__ import annotations

import argparse
import compileall
import json
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tools.check", description=__doc__)
    ap.add_argument("--baseline", action="store_true",
                    help="rewrite the baseline file from current findings")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip the compileall pass (pure lint)")
    ap.add_argument("--no-native", action="store_true",
                    help="skip the native toolchain smoke (build + ABI)")
    ap.add_argument("--latency", action="store_true",
                    help="also run the slow express-lane latency smoke "
                         "(tests/test_latency_smoke.py; real sockets, ~30s)")
    ap.add_argument("--trace-schema", action="store_true",
                    help="also validate the trace-export schema on a tiny "
                         "traced run (telemetry/trace_export --selftest)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset, e.g. GC01,GC04")
    args = ap.parse_args(argv)

    sys.path.insert(0, str(REPO_ROOT))
    from livekit_server_tpu.analysis import (
        core,
        diff_baseline,
        load_baseline,
        load_project,
        run_all,
        write_baseline,
    )

    t0 = time.perf_counter()
    config = core.load_config(REPO_ROOT)
    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        bad = [r for r in rules if r not in core.RULES]
        if bad:
            print(f"unknown rules: {', '.join(bad)}", file=sys.stderr)
            return 2
    project = load_project(REPO_ROOT, config.paths)
    findings = run_all(project, config, rules)

    baseline_path = REPO_ROOT / config.baseline
    if args.baseline:
        write_baseline(baseline_path, findings, project)
        print(f"baseline written: {len(findings)} finding(s) -> "
              f"{config.baseline}")
        return 0

    new, stale = diff_baseline(findings, load_baseline(baseline_path), project)

    # Bytecode-compile the tree: catches syntax errors in files the
    # analyzers never import (plugins, dead branches) — cheap and total.
    compiled_ok = True
    if not args.no_compile:
        compiled_ok = compileall.compile_dir(
            str(REPO_ROOT / "livekit_server_tpu"), quiet=2, force=False
        )

    # Native toolchain smoke: compile every native/*.cpp, load the .so's,
    # cross-check the baked ABI version symbols against the ctypes layer,
    # and run one tiny build/walk through each library. Catches a broken
    # compiler, a stale .so after an ABI bump, and signature drift —
    # failures the pure-Python gates above can't see.
    native_failures: list[str] = []
    if not args.no_native:
        try:
            from livekit_server_tpu import native as native_mod

            native_failures = native_mod.native_smoke()
        except Exception as exc:  # toolchain totally absent ⇒ report, fail
            native_failures = [f"native smoke crashed: {exc!r}"]

    # Opt-in latency smoke: the slow-marked express-lane wire-p99 test
    # (excluded from tier-1 by the `slow` marker). Runs in a subprocess
    # so a hung serving loop can't wedge the gate.
    latency_failures: list[str] = []
    if args.latency:
        import os
        import subprocess

        proc = subprocess.run(
            [sys.executable, "-m", "pytest",
             "tests/test_latency_smoke.py", "-q", "-m", "slow",
             "-p", "no:cacheprovider"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": os.environ.get(
                "JAX_PLATFORMS", "cpu")},
        )
        if proc.returncode != 0:
            tail = "\n".join((proc.stdout or "").splitlines()[-15:])
            latency_failures = [f"latency smoke failed "
                                f"(exit {proc.returncode}):\n{tail}"]
    native_failures.extend(latency_failures)

    # Opt-in trace-schema gate: run a tiny CPU plane with tracing on,
    # export the span ring as Chrome trace JSON, and validate required
    # fields + strict span nesting. Subprocess for the same hang-proofing
    # as the latency smoke.
    if args.trace_schema:
        import os
        import subprocess

        proc = subprocess.run(
            [sys.executable, "-m",
             "livekit_server_tpu.telemetry.trace_export", "--selftest"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": os.environ.get(
                "JAX_PLATFORMS", "cpu")},
        )
        if proc.returncode != 0:
            tail = "\n".join((proc.stdout or "").splitlines()[-15:])
            native_failures.append(
                f"trace schema selftest failed "
                f"(exit {proc.returncode}):\n{tail}"
            )

    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in new],
            "stale_baseline": stale,
            "compile_ok": bool(compiled_ok),
            "native_failures": native_failures,
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"STALE baseline entry (fixed? remove it): "
                  f"{e.get('rule')} {e.get('path')}: {e.get('content')}")
        if not compiled_ok:
            print("compileall: errors (see above)")
        for msg in native_failures:
            print(f"native: {msg}")
        dt = time.perf_counter() - t0
        ok = not (new or stale or native_failures) and compiled_ok
        status = "clean" if ok else "FAILED"
        print(f"graftcheck: {len(new)} finding(s), {len(stale)} stale "
              f"baseline entr(ies), {len(native_failures)} native "
              f"failure(s), {len(project.files)} files in "
              f"{dt:.2f}s — {status}")

    if stale:
        return 2
    if new or not compiled_ok or native_failures:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
