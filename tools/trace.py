"""Trace fetcher/validator CLI for the flight-recorder plane.

    python -m tools.trace --url http://127.0.0.1:7880 -o trace.json
    python -m tools.trace --selftest
    python -m tools.trace --validate trace.json

Fetches /debug/trace from a running node (the tick-span ring rendered as
Chrome/Perfetto trace-event JSON plus the sampled wire-latency stage
decomposition sidecar), writes it to a file loadable in ui.perfetto.dev
or chrome://tracing, and prints the stage summary. --validate re-checks
a saved export against the schema (required fields, non-negative
durations, strict span nesting per lane); --selftest runs a tiny traced
plane locally with no server at all.

Exit codes: 0 ok, 1 validation problems / fetch errors, 2 bad usage.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tools.trace", description=__doc__)
    ap.add_argument("--url", default="http://127.0.0.1:7880",
                    help="server base URL (default http://127.0.0.1:7880)")
    ap.add_argument("--ticks", type=int, default=120,
                    help="newest N ticks to export (default 120)")
    ap.add_argument("-o", "--out", default="trace.json",
                    help="output file for the fetched trace (default "
                         "trace.json)")
    ap.add_argument("--validate", metavar="FILE",
                    help="validate a saved trace JSON file instead of "
                         "fetching")
    ap.add_argument("--selftest", action="store_true",
                    help="run a tiny local traced plane and validate its "
                         "export (no server needed)")
    args = ap.parse_args(argv)

    from livekit_server_tpu.telemetry import trace_export

    if args.selftest:
        problems = trace_export.selftest()
        for p in problems:
            print(p)
        print("trace selftest:", "FAILED" if problems else "ok")
        return 1 if problems else 0

    if args.validate:
        with open(args.validate, encoding="utf-8") as fh:
            doc = json.load(fh)
        events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
        problems = trace_export.validate(events)
        for p in problems:
            print(p)
        print(f"trace: {len(events)} events, {len(problems)} problem(s)")
        return 1 if problems else 0

    url = f"{args.url.rstrip('/')}/debug/trace?ticks={args.ticks}"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            doc = json.load(resp)
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"fetch failed: {url}: {e}", file=sys.stderr)
        return 1
    events = doc.get("traceEvents", [])
    problems = trace_export.validate(events)
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    print(f"wrote {args.out}: {len(events)} events "
          f"({args.ticks} ticks requested)")
    stages = (doc.get("otherData") or {}).get("wire_stages") or {}
    for stage, s in stages.items():
        print(f"  {stage:8s} p50={s.get('p50_ms')}ms "
              f"p99={s.get('p99_ms')}ms n={s.get('n')}")
    for p in problems:
        print(p)
    if problems:
        print(f"validation: {len(problems)} problem(s)")
        return 1
    print("load it in ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
